/* fastmodel: C accelerators for the snapshot hot path.
 *
 * The per-cycle Snapshot clones every TaskInfo (50k at the north-star
 * scale); TaskInfo.clone is a verbatim slot copy (all fields shared by
 * reference — see models/job_info.py TaskInfo.clone), which in C is a
 * fixed set of pointer copies + increfs instead of ~18 interpreted
 * attribute assignments.  clone_task_table() clones a whole job's task
 * dict and builds the status index in one pass (the reference pays the
 * same via deepcopy-gen, cache.go:827-876).
 *
 * The slot offsets are read from the class's member descriptors at
 * registration time, so the layout always matches the Python definition.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <structmember.h>

#define MAX_SLOTS 64

static PyTypeObject *task_type = NULL;
static Py_ssize_t task_offsets[MAX_SLOTS];
static int n_task_slots = -1;
static Py_ssize_t status_offset = -1;
static Py_ssize_t uid_offset = -1;

/* Collect the member-descriptor offsets of every slot an instance of tp
 * carries — walking the whole MRO, not just tp's own __slots__, so a
 * subclass of a slotted model registers ALL storage (its own slots plus
 * the inherited ones). A clone that copied only the leaf class's slots
 * would silently leave the base's fields NULL. Any MRO entry (other
 * than object) WITHOUT __slots__ rejects the registration: it gives
 * instances a __dict__ this copier would not clone. Optionally reports
 * the offsets of up to two named slots (want_a/want_b, NULL to skip).
 * Writes ONLY into caller-provided storage so a failed registration can
 * commit atomically. */
static int
collect_one_class(PyTypeObject *tp, PyObject *klass, Py_ssize_t *offsets,
                  int *count, const char *want_a, Py_ssize_t *off_a,
                  const char *want_b, Py_ssize_t *off_b)
{
    PyObject *slots = PyObject_GetAttrString(klass, "__slots__");
    if (slots == NULL) {
        PyErr_Format(PyExc_TypeError,
                     "%s in the MRO of %s has no __slots__ (instances "
                     "would carry a __dict__ the slot copier cannot "
                     "clone)",
                     ((PyTypeObject *)klass)->tp_name, tp->tp_name);
        return -1;
    }
    /* a bare-string __slots__ declares ONE slot, not len(str) of them */
    if (PyUnicode_Check(slots)) {
        PyObject *tup = PyTuple_Pack(1, slots);
        Py_DECREF(slots);
        if (tup == NULL)
            return -1;
        slots = tup;
    }
    PyObject *seq = PySequence_Fast(slots, "__slots__ not a sequence");
    Py_DECREF(slots);
    if (seq == NULL)
        return -1;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *name = PySequence_Fast_GET_ITEM(seq, i);
        /* resolve through tp, not klass: a shadowed name must land on
         * the storage the instance actually uses */
        PyObject *descr = PyObject_GetAttr((PyObject *)tp, name);
        if (descr == NULL) {
            Py_DECREF(seq);
            return -1;
        }
        if (Py_TYPE(descr) != &PyMemberDescr_Type) {
            Py_DECREF(descr);
            Py_DECREF(seq);
            PyErr_SetString(PyExc_TypeError,
                            "slot attr is not a member descriptor");
            return -1;
        }
        PyMemberDef *m = ((PyMemberDescrObject *)descr)->d_member;
        Py_DECREF(descr);
        int dup = 0;
        for (int j = 0; j < *count; j++)
            if (offsets[j] == m->offset) { dup = 1; break; }
        if (!dup) {
            if (*count >= MAX_SLOTS) {
                Py_DECREF(seq);
                PyErr_SetString(PyExc_ValueError, "too many slots");
                return -1;
            }
            offsets[(*count)++] = m->offset;
        }
        const char *cname = PyUnicode_AsUTF8(name);
        if (cname != NULL) {
            if (want_a != NULL && strcmp(cname, want_a) == 0)
                *off_a = m->offset;
            if (want_b != NULL && strcmp(cname, want_b) == 0)
                *off_b = m->offset;
        }
    }
    Py_DECREF(seq);
    return 0;
}

static int
collect_offsets(PyTypeObject *tp, Py_ssize_t *offsets, int *count,
                const char *want_a, Py_ssize_t *off_a,
                const char *want_b, Py_ssize_t *off_b)
{
    /* the authoritative __dict__ check: ANY slotless class in the
     * hierarchy (including a subclass that merely inherits __slots__
     * without declaring its own) gives instances a dict, and dict state
     * is invisible to the slot copier. tp_dictoffset is how the
     * interpreter itself records that. */
    if (tp->tp_dictoffset != 0) {
        PyErr_Format(PyExc_TypeError,
                     "%s instances carry a __dict__ (some class in the "
                     "hierarchy lacks __slots__); the slot copier would "
                     "clone it partially", tp->tp_name);
        return -1;
    }
    PyObject *mro = tp->tp_mro;
    if (mro == NULL || !PyTuple_Check(mro)) {
        PyErr_SetString(PyExc_TypeError, "type has no MRO");
        return -1;
    }
    *count = 0;
    for (Py_ssize_t k = 0; k < PyTuple_GET_SIZE(mro); k++) {
        PyObject *klass = PyTuple_GET_ITEM(mro, k);
        if (klass == (PyObject *)&PyBaseObject_Type)
            continue;
        if (collect_one_class(tp, klass, offsets, count,
                              want_a, off_a, want_b, off_b) < 0)
            return -1;
    }
    return 0;
}

static PyObject *
register_task_type(PyObject *self, PyObject *arg)
{
    if (!PyType_Check(arg)) {
        PyErr_SetString(PyExc_TypeError, "expected a type");
        return NULL;
    }
    PyTypeObject *tp = (PyTypeObject *)arg;
    /* stage into locals; commit globals only on full success */
    Py_ssize_t offsets[MAX_SLOTS];
    int count = 0;
    Py_ssize_t st_off = -1, u_off = -1;
    if (collect_offsets(tp, offsets, &count, "status", &st_off,
                        "uid", &u_off) < 0)
        return NULL;
    if (st_off < 0 || u_off < 0) {
        PyErr_SetString(PyExc_ValueError, "type lacks status/uid slots");
        return NULL;
    }
    memcpy(task_offsets, offsets, sizeof(offsets[0]) * count);
    n_task_slots = count;
    status_offset = st_off;
    uid_offset = u_off;
    Py_XDECREF((PyObject *)task_type);
    Py_INCREF(arg);
    task_type = tp;
    Py_RETURN_NONE;
}

static inline PyObject *
clone_one(PyObject *src)
{
    PyObject *dst = task_type->tp_alloc(task_type, 0);
    if (dst == NULL)
        return NULL;
    char *s = (char *)src, *d = (char *)dst;
    for (int i = 0; i < n_task_slots; i++) {
        PyObject *v = *(PyObject **)(s + task_offsets[i]);
        Py_XINCREF(v);
        *(PyObject **)(d + task_offsets[i]) = v;
    }
    return dst;
}

static PyObject *
clone_task(PyObject *self, PyObject *arg)
{
    if (n_task_slots < 0 || Py_TYPE(arg) != task_type) {
        PyErr_SetString(PyExc_TypeError, "not a registered TaskInfo");
        return NULL;
    }
    return clone_one(arg);
}

/* clone_task_table(tasks: dict[uid, TaskInfo])
 *    -> (new_tasks: dict, index: dict[status, dict[uid, TaskInfo]])
 * Exact tasks must be the registered type (callers guarantee it). */
static PyObject *
clone_task_table(PyObject *self, PyObject *arg)
{
    if (n_task_slots < 0) {
        PyErr_SetString(PyExc_RuntimeError, "task type not registered");
        return NULL;
    }
    if (!PyDict_CheckExact(arg)) {
        PyErr_SetString(PyExc_TypeError, "expected a dict");
        return NULL;
    }
    PyObject *new_tasks = PyDict_New();
    PyObject *index = PyDict_New();
    if (new_tasks == NULL || index == NULL)
        goto fail;
    Py_ssize_t pos = 0;
    PyObject *key, *value;
    while (PyDict_Next(arg, &pos, &key, &value)) {
        if (Py_TYPE(value) != task_type) {
            PyErr_SetString(PyExc_TypeError, "mixed task types");
            goto fail;
        }
        PyObject *c = clone_one(value);
        if (c == NULL)
            goto fail;
        if (PyDict_SetItem(new_tasks, key, c) < 0) {
            Py_DECREF(c);
            goto fail;
        }
        PyObject *status = *(PyObject **)((char *)c + status_offset);
        PyObject *bucket = PyDict_GetItemWithError(index, status);
        if (bucket == NULL) {
            if (PyErr_Occurred()) {
                Py_DECREF(c);
                goto fail;
            }
            bucket = PyDict_New();
            if (bucket == NULL || PyDict_SetItem(index, status, bucket) < 0) {
                Py_XDECREF(bucket);
                Py_DECREF(c);
                goto fail;
            }
            Py_DECREF(bucket);  /* index holds it */
        }
        if (PyDict_SetItem(bucket, key, c) < 0) {
            Py_DECREF(c);
            goto fail;
        }
        Py_DECREF(c);
    }
    return Py_BuildValue("(NN)", new_tasks, index);
fail:
    Py_XDECREF(new_tasks);
    Py_XDECREF(index);
    return NULL;
}

/* clone_task_dict(tasks) -> dict of cloned tasks (no status index) —
 * NodeInfo.tasks clones. */
static PyObject *
clone_task_dict(PyObject *self, PyObject *arg)
{
    if (n_task_slots < 0 || !PyDict_CheckExact(arg)) {
        PyErr_SetString(PyExc_TypeError, "expected a dict (registered type)");
        return NULL;
    }
    PyObject *out = PyDict_New();
    if (out == NULL)
        return NULL;
    Py_ssize_t pos = 0;
    PyObject *key, *value;
    while (PyDict_Next(arg, &pos, &key, &value)) {
        if (Py_TYPE(value) != task_type) {
            Py_DECREF(out);
            PyErr_SetString(PyExc_TypeError, "mixed task types");
            return NULL;
        }
        PyObject *c = clone_one(value);
        if (c == NULL || PyDict_SetItem(out, key, c) < 0) {
            Py_XDECREF(c);
            Py_DECREF(out);
            return NULL;
        }
        Py_DECREF(c);
    }
    return out;
}

/* ---- Resource (slots: milli_cpu, memory, scalars, max_task_num) ---- */

static PyTypeObject *res_type = NULL;
static Py_ssize_t res_offsets[MAX_SLOTS];
static int n_res_slots = -1;
static Py_ssize_t res_scalars_offset = -1;

static PyObject *
register_resource_type(PyObject *self, PyObject *arg)
{
    if (!PyType_Check(arg)) {
        PyErr_SetString(PyExc_TypeError, "expected a type");
        return NULL;
    }
    PyTypeObject *tp = (PyTypeObject *)arg;
    /* stage into locals; commit globals only on full success */
    Py_ssize_t offsets[MAX_SLOTS];
    int count = 0;
    Py_ssize_t sc_off = -1;
    if (collect_offsets(tp, offsets, &count, "scalars", &sc_off,
                        NULL, NULL) < 0)
        return NULL;
    if (sc_off < 0) {
        PyErr_SetString(PyExc_ValueError, "type lacks a scalars slot");
        return NULL;
    }
    memcpy(res_offsets, offsets, sizeof(offsets[0]) * count);
    n_res_slots = count;
    res_scalars_offset = sc_off;
    Py_XDECREF((PyObject *)res_type);
    Py_INCREF(arg);
    res_type = tp;
    Py_RETURN_NONE;
}

static PyObject *
clone_resource(PyObject *self, PyObject *arg)
{
    if (n_res_slots < 0 || Py_TYPE(arg) != res_type) {
        PyErr_SetString(PyExc_TypeError, "not a registered Resource");
        return NULL;
    }
    PyObject *dst = res_type->tp_alloc(res_type, 0);
    if (dst == NULL)
        return NULL;
    char *s = (char *)arg, *d = (char *)dst;
    for (int i = 0; i < n_res_slots; i++) {
        PyObject *v = *(PyObject **)(s + res_offsets[i]);
        if (res_offsets[i] == res_scalars_offset && v != NULL) {
            PyObject *copy = PyDict_Copy(v);
            if (copy == NULL) {
                Py_DECREF(dst);
                return NULL;
            }
            *(PyObject **)(d + res_offsets[i]) = copy;
        } else {
            Py_XINCREF(v);
            *(PyObject **)(d + res_offsets[i]) = v;
        }
    }
    return dst;
}

/* ---- generic shell clone for plain __dict__ classes ---- */

/* interned attribute keys for the bind-clone hot loop (module init) */
static PyObject *s_metadata, *s_spec, *s_node_name, *s_resource_version;

/* instance __dict__ slot of o, or NULL (with TypeError set) when the
 * class keeps no dict — the bind-clone loop works on the dict storage
 * directly, skipping the attribute-descriptor machinery entirely */
static PyObject **
dict_slot(PyObject *o)
{
    PyObject **dp = _PyObject_GetDictPtr(o);
    if (dp == NULL)
        PyErr_Format(PyExc_TypeError, "%s instance carries no __dict__",
                     Py_TYPE(o)->tp_name);
    return dp;
}

/* new instance of tp adopting nd as its __dict__ (steals no refs;
 * the instance takes its own). NULL on failure. */
static PyObject *
adopt_dict(PyTypeObject *tp, PyObject *nd)
{
    PyObject *dst = tp->tp_alloc(tp, 0);
    if (dst == NULL)
        return NULL;
    PyObject **dp = _PyObject_GetDictPtr(dst);
    if (dp == NULL) {
        Py_DECREF(dst);
        PyErr_Format(PyExc_TypeError, "%s instances carry no __dict__",
                     tp->tp_name);
        return NULL;
    }
    Py_INCREF(nd);
    *dp = nd;
    return dst;
}

/* bind_clone_pods(pods, hostnames, rv_start) -> list[Pod]
 *
 * The whole clone+patch+rv step of one bind-flush shard in a single
 * call: for each stored pod, build the minimal bind clone (the C twin of
 * models/objects.py clone_pod_for_bind — fresh pod/metadata/spec shells,
 * every subtree SHARED with the immutable stored object, the _rr parse
 * cache riding along in the dict copy), set spec.node_name to
 * hostnames[i] and metadata.resource_version to rv_start + i.  The
 * Python loop pays ~6 dict builds + 3 object constructions + 2 attribute
 * stores per pod in interpreted code; here it is a fixed sequence of
 * C-API calls, which is what turns the 50k-pod store pass from the
 * flush's dominant cost into a minor one (docs/design/bind_pipeline.md).
 */
static PyObject *
bind_clone_pods(PyObject *self, PyObject *args)
{
    PyObject *pods, *hosts;
    long long rv_start;
    if (!PyArg_ParseTuple(args, "O!O!L", &PyList_Type, &pods,
                          &PyList_Type, &hosts, &rv_start))
        return NULL;
    Py_ssize_t n = PyList_GET_SIZE(pods);
    if (PyList_GET_SIZE(hosts) != n) {
        PyErr_SetString(PyExc_ValueError, "pods/hostnames length mismatch");
        return NULL;
    }
    PyObject *out = PyList_New(n);
    if (out == NULL)
        return NULL;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *src = PyList_GET_ITEM(pods, i);
        PyObject **sdp = dict_slot(src);
        if (sdp == NULL || *sdp == NULL) {
            if (sdp != NULL)
                PyErr_SetString(PyExc_TypeError, "pod has no __dict__");
            goto fail;
        }
        PyObject *nd = PyDict_Copy(*sdp);
        if (nd == NULL)
            goto fail;
        /* metadata shell with the fresh resource_version */
        PyObject *meta = PyDict_GetItem(nd, s_metadata); /* borrowed */
        PyObject *spec = PyDict_GetItem(nd, s_spec);     /* borrowed */
        PyObject **mdp, **spp;
        if (meta == NULL || spec == NULL ||
            (mdp = dict_slot(meta)) == NULL || *mdp == NULL ||
            (spp = dict_slot(spec)) == NULL || *spp == NULL) {
            if (!PyErr_Occurred())
                PyErr_SetString(PyExc_TypeError,
                                "pod lacks metadata/spec dicts");
            Py_DECREF(nd);
            goto fail;
        }
        PyObject *md = PyDict_Copy(*mdp);
        PyObject *rv = PyLong_FromLongLong(rv_start + (long long)i);
        PyObject *nmeta = NULL;
        if (md == NULL || rv == NULL ||
            PyDict_SetItem(md, s_resource_version, rv) < 0 ||
            (nmeta = adopt_dict(Py_TYPE(meta), md)) == NULL ||
            PyDict_SetItem(nd, s_metadata, nmeta) < 0) {
            Py_XDECREF(nmeta);
            Py_XDECREF(rv);
            Py_XDECREF(md);
            Py_DECREF(nd);
            goto fail;
        }
        Py_DECREF(nmeta);
        Py_DECREF(rv);
        Py_DECREF(md);
        /* spec shell with the bind target */
        PyObject *sd = PyDict_Copy(*spp);
        PyObject *nspec = NULL;
        if (sd == NULL ||
            PyDict_SetItem(sd, s_node_name, PyList_GET_ITEM(hosts, i)) < 0 ||
            (nspec = adopt_dict(Py_TYPE(spec), sd)) == NULL ||
            PyDict_SetItem(nd, s_spec, nspec) < 0) {
            Py_XDECREF(nspec);
            Py_XDECREF(sd);
            Py_DECREF(nd);
            goto fail;
        }
        Py_DECREF(nspec);
        Py_DECREF(sd);
        PyObject *dst = adopt_dict(Py_TYPE(src), nd);
        Py_DECREF(nd);
        if (dst == NULL)
            goto fail;
        PyList_SET_ITEM(out, i, dst);
    }
    return out;
fail:
    Py_DECREF(out);
    return NULL;
}

static PyObject *
shell_clone(PyObject *self, PyObject *src)
{
    PyTypeObject *tp = Py_TYPE(src);
    PyObject *d = PyObject_GetAttrString(src, "__dict__");
    if (d == NULL)
        return NULL;
    PyObject *nd = PyDict_Copy(d);
    Py_DECREF(d);
    if (nd == NULL)
        return NULL;
    PyObject *dst = tp->tp_alloc(tp, 0);
    if (dst == NULL) {
        Py_DECREF(nd);
        return NULL;
    }
    if (PyObject_SetAttrString(dst, "__dict__", nd) < 0) {
        Py_DECREF(nd);
        Py_DECREF(dst);
        return NULL;
    }
    Py_DECREF(nd);
    return dst;
}

static PyMethodDef methods[] = {
    {"register_task_type", register_task_type, METH_O,
     "Register the TaskInfo class (reads slot offsets)."},
    {"clone_task", clone_task, METH_O, "Verbatim slot-copy clone."},
    {"clone_task_table", clone_task_table, METH_O,
     "Clone a job's task dict and build the status index."},
    {"clone_task_dict", clone_task_dict, METH_O,
     "Clone a node's task dict (no index)."},
    {"register_resource_type", register_resource_type, METH_O,
     "Register the Resource class (reads slot offsets)."},
    {"clone_resource", clone_resource, METH_O,
     "Slot-copy Resource clone with a fresh scalars dict."},
    {"shell_clone", shell_clone, METH_O,
     "New instance of type(obj) with a shallow __dict__ copy."},
    {"bind_clone_pods", bind_clone_pods, METH_VARARGS,
     "Batch bind clone: minimal pod shells with node_name + rv set."},
    {NULL, NULL, 0, NULL}
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "fastmodel",
    "C accelerators for snapshot cloning.", -1, methods
};

PyMODINIT_FUNC
PyInit_fastmodel(void)
{
    s_metadata = PyUnicode_InternFromString("metadata");
    s_spec = PyUnicode_InternFromString("spec");
    s_node_name = PyUnicode_InternFromString("node_name");
    s_resource_version = PyUnicode_InternFromString("resource_version");
    if (s_metadata == NULL || s_spec == NULL || s_node_name == NULL ||
        s_resource_version == NULL)
        return NULL;
    return PyModule_Create(&moduledef);
}
