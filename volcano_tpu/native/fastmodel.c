/* fastmodel: C accelerators for the snapshot hot path.
 *
 * The per-cycle Snapshot clones every TaskInfo (50k at the north-star
 * scale); TaskInfo.clone is a verbatim slot copy (all fields shared by
 * reference — see models/job_info.py TaskInfo.clone), which in C is a
 * fixed set of pointer copies + increfs instead of ~18 interpreted
 * attribute assignments.  clone_task_table() clones a whole job's task
 * dict and builds the status index in one pass (the reference pays the
 * same via deepcopy-gen, cache.go:827-876).
 *
 * The slot offsets are read from the class's member descriptors at
 * registration time, so the layout always matches the Python definition.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <structmember.h>
#include <math.h>

#define MAX_SLOTS 64

static PyTypeObject *task_type = NULL;
static Py_ssize_t task_offsets[MAX_SLOTS];
static int n_task_slots = -1;
static Py_ssize_t status_offset = -1;
static Py_ssize_t uid_offset = -1;
/* extra named TaskInfo slots for the bind-echo/apply passes */
static Py_ssize_t t_node_name_off = -1, t_job_off = -1, t_pod_off = -1,
                  t_namespace_off = -1, t_name_off = -1, t_resreq_off = -1,
                  t_key_off = -1;

/* offset of one named slot's member descriptor on tp (resolved through
 * tp so shadowed names land on the instance's real storage); -1 with an
 * exception set on failure */
static Py_ssize_t
member_offset(PyTypeObject *tp, const char *name)
{
    PyObject *descr = PyObject_GetAttrString((PyObject *)tp, name);
    if (descr == NULL)
        return -1;
    if (Py_TYPE(descr) != &PyMemberDescr_Type) {
        Py_DECREF(descr);
        PyErr_Format(PyExc_TypeError, "%s.%s is not a slot descriptor",
                     tp->tp_name, name);
        return -1;
    }
    Py_ssize_t off = ((PyMemberDescrObject *)descr)->d_member->offset;
    Py_DECREF(descr);
    return off;
}

/* Collect the member-descriptor offsets of every slot an instance of tp
 * carries — walking the whole MRO, not just tp's own __slots__, so a
 * subclass of a slotted model registers ALL storage (its own slots plus
 * the inherited ones). A clone that copied only the leaf class's slots
 * would silently leave the base's fields NULL. Any MRO entry (other
 * than object) WITHOUT __slots__ rejects the registration: it gives
 * instances a __dict__ this copier would not clone. Optionally reports
 * the offsets of up to two named slots (want_a/want_b, NULL to skip).
 * Writes ONLY into caller-provided storage so a failed registration can
 * commit atomically. */
static int
collect_one_class(PyTypeObject *tp, PyObject *klass, Py_ssize_t *offsets,
                  int *count, const char *want_a, Py_ssize_t *off_a,
                  const char *want_b, Py_ssize_t *off_b)
{
    PyObject *slots = PyObject_GetAttrString(klass, "__slots__");
    if (slots == NULL) {
        PyErr_Format(PyExc_TypeError,
                     "%s in the MRO of %s has no __slots__ (instances "
                     "would carry a __dict__ the slot copier cannot "
                     "clone)",
                     ((PyTypeObject *)klass)->tp_name, tp->tp_name);
        return -1;
    }
    /* a bare-string __slots__ declares ONE slot, not len(str) of them */
    if (PyUnicode_Check(slots)) {
        PyObject *tup = PyTuple_Pack(1, slots);
        Py_DECREF(slots);
        if (tup == NULL)
            return -1;
        slots = tup;
    }
    PyObject *seq = PySequence_Fast(slots, "__slots__ not a sequence");
    Py_DECREF(slots);
    if (seq == NULL)
        return -1;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *name = PySequence_Fast_GET_ITEM(seq, i);
        /* resolve through tp, not klass: a shadowed name must land on
         * the storage the instance actually uses */
        PyObject *descr = PyObject_GetAttr((PyObject *)tp, name);
        if (descr == NULL) {
            Py_DECREF(seq);
            return -1;
        }
        if (Py_TYPE(descr) != &PyMemberDescr_Type) {
            Py_DECREF(descr);
            Py_DECREF(seq);
            PyErr_SetString(PyExc_TypeError,
                            "slot attr is not a member descriptor");
            return -1;
        }
        PyMemberDef *m = ((PyMemberDescrObject *)descr)->d_member;
        Py_DECREF(descr);
        int dup = 0;
        for (int j = 0; j < *count; j++)
            if (offsets[j] == m->offset) { dup = 1; break; }
        if (!dup) {
            if (*count >= MAX_SLOTS) {
                Py_DECREF(seq);
                PyErr_SetString(PyExc_ValueError, "too many slots");
                return -1;
            }
            offsets[(*count)++] = m->offset;
        }
        const char *cname = PyUnicode_AsUTF8(name);
        if (cname != NULL) {
            if (want_a != NULL && strcmp(cname, want_a) == 0)
                *off_a = m->offset;
            if (want_b != NULL && strcmp(cname, want_b) == 0)
                *off_b = m->offset;
        }
    }
    Py_DECREF(seq);
    return 0;
}

static int
collect_offsets(PyTypeObject *tp, Py_ssize_t *offsets, int *count,
                const char *want_a, Py_ssize_t *off_a,
                const char *want_b, Py_ssize_t *off_b)
{
    /* the authoritative __dict__ check: ANY slotless class in the
     * hierarchy (including a subclass that merely inherits __slots__
     * without declaring its own) gives instances a dict, and dict state
     * is invisible to the slot copier. tp_dictoffset is how the
     * interpreter itself records that. */
    if (tp->tp_dictoffset != 0) {
        PyErr_Format(PyExc_TypeError,
                     "%s instances carry a __dict__ (some class in the "
                     "hierarchy lacks __slots__); the slot copier would "
                     "clone it partially", tp->tp_name);
        return -1;
    }
    PyObject *mro = tp->tp_mro;
    if (mro == NULL || !PyTuple_Check(mro)) {
        PyErr_SetString(PyExc_TypeError, "type has no MRO");
        return -1;
    }
    *count = 0;
    for (Py_ssize_t k = 0; k < PyTuple_GET_SIZE(mro); k++) {
        PyObject *klass = PyTuple_GET_ITEM(mro, k);
        if (klass == (PyObject *)&PyBaseObject_Type)
            continue;
        if (collect_one_class(tp, klass, offsets, count,
                              want_a, off_a, want_b, off_b) < 0)
            return -1;
    }
    return 0;
}

static PyObject *
register_task_type(PyObject *self, PyObject *arg)
{
    if (!PyType_Check(arg)) {
        PyErr_SetString(PyExc_TypeError, "expected a type");
        return NULL;
    }
    PyTypeObject *tp = (PyTypeObject *)arg;
    /* stage into locals; commit globals only on full success */
    Py_ssize_t offsets[MAX_SLOTS];
    int count = 0;
    Py_ssize_t st_off = -1, u_off = -1;
    if (collect_offsets(tp, offsets, &count, "status", &st_off,
                        "uid", &u_off) < 0)
        return NULL;
    if (st_off < 0 || u_off < 0) {
        PyErr_SetString(PyExc_ValueError, "type lacks status/uid slots");
        return NULL;
    }
    Py_ssize_t nn_off = member_offset(tp, "node_name");
    Py_ssize_t j_off = member_offset(tp, "job");
    Py_ssize_t p_off = member_offset(tp, "pod");
    Py_ssize_t ns_off = member_offset(tp, "namespace");
    Py_ssize_t nm_off = member_offset(tp, "name");
    Py_ssize_t rr_off = member_offset(tp, "resreq");
    Py_ssize_t k_off = member_offset(tp, "key_cache");
    if (nn_off < 0 || j_off < 0 || p_off < 0 || ns_off < 0 || nm_off < 0 ||
        rr_off < 0 || k_off < 0)
        return NULL;
    memcpy(task_offsets, offsets, sizeof(offsets[0]) * count);
    n_task_slots = count;
    status_offset = st_off;
    uid_offset = u_off;
    t_node_name_off = nn_off;
    t_job_off = j_off;
    t_pod_off = p_off;
    t_namespace_off = ns_off;
    t_name_off = nm_off;
    t_resreq_off = rr_off;
    t_key_off = k_off;
    Py_XDECREF((PyObject *)task_type);
    Py_INCREF(arg);
    task_type = tp;
    Py_RETURN_NONE;
}

/* ---- TaskStatus members + allocated set (bind-echo pass) ---- */

static PyObject *ts_running = NULL, *ts_releasing = NULL, *ts_bound = NULL,
                *ts_pending = NULL, *ts_succeeded = NULL, *ts_failed = NULL,
                *ts_unknown = NULL, *ts_allocated_set = NULL;

/* register_task_status(TaskStatus, allocated_statuses): capture the enum
 * members the C twin of job_info.get_task_status hands back, plus the
 * allocated-status set. */
static PyObject *
register_task_status(PyObject *self, PyObject *args)
{
    PyObject *cls, *allocated;
    if (!PyArg_ParseTuple(args, "OO", &cls, &allocated))
        return NULL;
    PyObject *run = PyObject_GetAttrString(cls, "Running");
    PyObject *rel = PyObject_GetAttrString(cls, "Releasing");
    PyObject *bnd = PyObject_GetAttrString(cls, "Bound");
    PyObject *pen = PyObject_GetAttrString(cls, "Pending");
    PyObject *suc = PyObject_GetAttrString(cls, "Succeeded");
    PyObject *fai = PyObject_GetAttrString(cls, "Failed");
    PyObject *unk = PyObject_GetAttrString(cls, "Unknown");
    if (run == NULL || rel == NULL || bnd == NULL || pen == NULL ||
        suc == NULL || fai == NULL || unk == NULL) {
        Py_XDECREF(run); Py_XDECREF(rel); Py_XDECREF(bnd); Py_XDECREF(pen);
        Py_XDECREF(suc); Py_XDECREF(fai); Py_XDECREF(unk);
        return NULL;
    }
    PyObject *alloc_set = PySet_New(allocated);
    if (alloc_set == NULL) {
        Py_DECREF(run); Py_DECREF(rel); Py_DECREF(bnd); Py_DECREF(pen);
        Py_DECREF(suc); Py_DECREF(fai); Py_DECREF(unk);
        return NULL;
    }
    Py_XDECREF(ts_running);   ts_running = run;
    Py_XDECREF(ts_releasing); ts_releasing = rel;
    Py_XDECREF(ts_bound);     ts_bound = bnd;
    Py_XDECREF(ts_pending);   ts_pending = pen;
    Py_XDECREF(ts_succeeded); ts_succeeded = suc;
    Py_XDECREF(ts_failed);    ts_failed = fai;
    Py_XDECREF(ts_unknown);   ts_unknown = unk;
    Py_XDECREF(ts_allocated_set); ts_allocated_set = alloc_set;
    Py_RETURN_NONE;
}

static inline PyObject *
clone_one(PyObject *src)
{
    PyObject *dst = task_type->tp_alloc(task_type, 0);
    if (dst == NULL)
        return NULL;
    char *s = (char *)src, *d = (char *)dst;
    for (int i = 0; i < n_task_slots; i++) {
        PyObject *v = *(PyObject **)(s + task_offsets[i]);
        Py_XINCREF(v);
        *(PyObject **)(d + task_offsets[i]) = v;
    }
    return dst;
}

static PyObject *
clone_task(PyObject *self, PyObject *arg)
{
    if (n_task_slots < 0 || Py_TYPE(arg) != task_type) {
        PyErr_SetString(PyExc_TypeError, "not a registered TaskInfo");
        return NULL;
    }
    return clone_one(arg);
}

/* clone_task_table(tasks: dict[uid, TaskInfo])
 *    -> (new_tasks: dict, index: dict[status, dict[uid, TaskInfo]])
 * Exact tasks must be the registered type (callers guarantee it). */
static PyObject *
clone_task_table(PyObject *self, PyObject *arg)
{
    if (n_task_slots < 0) {
        PyErr_SetString(PyExc_RuntimeError, "task type not registered");
        return NULL;
    }
    if (!PyDict_CheckExact(arg)) {
        PyErr_SetString(PyExc_TypeError, "expected a dict");
        return NULL;
    }
    PyObject *new_tasks = PyDict_New();
    PyObject *index = PyDict_New();
    if (new_tasks == NULL || index == NULL)
        goto fail;
    Py_ssize_t pos = 0;
    PyObject *key, *value;
    while (PyDict_Next(arg, &pos, &key, &value)) {
        if (Py_TYPE(value) != task_type) {
            PyErr_SetString(PyExc_TypeError, "mixed task types");
            goto fail;
        }
        PyObject *c = clone_one(value);
        if (c == NULL)
            goto fail;
        if (PyDict_SetItem(new_tasks, key, c) < 0) {
            Py_DECREF(c);
            goto fail;
        }
        PyObject *status = *(PyObject **)((char *)c + status_offset);
        PyObject *bucket = PyDict_GetItemWithError(index, status);
        if (bucket == NULL) {
            if (PyErr_Occurred()) {
                Py_DECREF(c);
                goto fail;
            }
            bucket = PyDict_New();
            if (bucket == NULL || PyDict_SetItem(index, status, bucket) < 0) {
                Py_XDECREF(bucket);
                Py_DECREF(c);
                goto fail;
            }
            Py_DECREF(bucket);  /* index holds it */
        }
        if (PyDict_SetItem(bucket, key, c) < 0) {
            Py_DECREF(c);
            goto fail;
        }
        Py_DECREF(c);
    }
    return Py_BuildValue("(NN)", new_tasks, index);
fail:
    Py_XDECREF(new_tasks);
    Py_XDECREF(index);
    return NULL;
}

/* clone_task_dict(tasks) -> dict of cloned tasks (no status index) —
 * NodeInfo.tasks clones. */
static PyObject *
clone_task_dict(PyObject *self, PyObject *arg)
{
    if (n_task_slots < 0 || !PyDict_CheckExact(arg)) {
        PyErr_SetString(PyExc_TypeError, "expected a dict (registered type)");
        return NULL;
    }
    PyObject *out = PyDict_New();
    if (out == NULL)
        return NULL;
    Py_ssize_t pos = 0;
    PyObject *key, *value;
    while (PyDict_Next(arg, &pos, &key, &value)) {
        if (Py_TYPE(value) != task_type) {
            Py_DECREF(out);
            PyErr_SetString(PyExc_TypeError, "mixed task types");
            return NULL;
        }
        PyObject *c = clone_one(value);
        if (c == NULL || PyDict_SetItem(out, key, c) < 0) {
            Py_XDECREF(c);
            Py_DECREF(out);
            return NULL;
        }
        Py_DECREF(c);
    }
    return out;
}

/* ---- Resource (slots: milli_cpu, memory, scalars, max_task_num) ---- */

static PyTypeObject *res_type = NULL;
static Py_ssize_t res_offsets[MAX_SLOTS];
static int n_res_slots = -1;
static Py_ssize_t res_scalars_offset = -1;
static Py_ssize_t res_cpu_offset = -1, res_mem_offset = -1;

static PyObject *
register_resource_type(PyObject *self, PyObject *arg)
{
    if (!PyType_Check(arg)) {
        PyErr_SetString(PyExc_TypeError, "expected a type");
        return NULL;
    }
    PyTypeObject *tp = (PyTypeObject *)arg;
    /* stage into locals; commit globals only on full success */
    Py_ssize_t offsets[MAX_SLOTS];
    int count = 0;
    Py_ssize_t sc_off = -1;
    if (collect_offsets(tp, offsets, &count, "scalars", &sc_off,
                        NULL, NULL) < 0)
        return NULL;
    if (sc_off < 0) {
        PyErr_SetString(PyExc_ValueError, "type lacks a scalars slot");
        return NULL;
    }
    Py_ssize_t cpu_off = member_offset(tp, "milli_cpu");
    Py_ssize_t mem_off = member_offset(tp, "memory");
    if (cpu_off < 0 || mem_off < 0)
        return NULL;
    memcpy(res_offsets, offsets, sizeof(offsets[0]) * count);
    n_res_slots = count;
    res_scalars_offset = sc_off;
    res_cpu_offset = cpu_off;
    res_mem_offset = mem_off;
    Py_XDECREF((PyObject *)res_type);
    Py_INCREF(arg);
    res_type = tp;
    Py_RETURN_NONE;
}

static PyObject *
clone_resource(PyObject *self, PyObject *arg)
{
    if (n_res_slots < 0 || Py_TYPE(arg) != res_type) {
        PyErr_SetString(PyExc_TypeError, "not a registered Resource");
        return NULL;
    }
    PyObject *dst = res_type->tp_alloc(res_type, 0);
    if (dst == NULL)
        return NULL;
    char *s = (char *)arg, *d = (char *)dst;
    for (int i = 0; i < n_res_slots; i++) {
        PyObject *v = *(PyObject **)(s + res_offsets[i]);
        if (res_offsets[i] == res_scalars_offset && v != NULL) {
            PyObject *copy = PyDict_Copy(v);
            if (copy == NULL) {
                Py_DECREF(dst);
                return NULL;
            }
            *(PyObject **)(d + res_offsets[i]) = copy;
        } else {
            Py_XINCREF(v);
            *(PyObject **)(d + res_offsets[i]) = v;
        }
    }
    return dst;
}

/* ---- generic shell clone for plain __dict__ classes ---- */

/* interned attribute keys for the bind-clone hot loop (module init) */
static PyObject *s_metadata, *s_spec, *s_node_name, *s_resource_version;

/* instance __dict__ slot of o, or NULL (with TypeError set) when the
 * class keeps no dict — the bind-clone loop works on the dict storage
 * directly, skipping the attribute-descriptor machinery entirely */
static PyObject **
dict_slot(PyObject *o)
{
    PyObject **dp = _PyObject_GetDictPtr(o);
    if (dp == NULL)
        PyErr_Format(PyExc_TypeError, "%s instance carries no __dict__",
                     Py_TYPE(o)->tp_name);
    return dp;
}

/* new instance of tp adopting nd as its __dict__ (steals no refs;
 * the instance takes its own). NULL on failure. */
static PyObject *
adopt_dict(PyTypeObject *tp, PyObject *nd)
{
    PyObject *dst = tp->tp_alloc(tp, 0);
    if (dst == NULL)
        return NULL;
    PyObject **dp = _PyObject_GetDictPtr(dst);
    if (dp == NULL) {
        Py_DECREF(dst);
        PyErr_Format(PyExc_TypeError, "%s instances carry no __dict__",
                     tp->tp_name);
        return NULL;
    }
    Py_INCREF(nd);
    *dp = nd;
    return dst;
}

/* bind_clone_pods(pods, hostnames, rv_start) -> list[Pod]
 *
 * The whole clone+patch+rv step of one bind-flush shard in a single
 * call: for each stored pod, build the minimal bind clone (the C twin of
 * models/objects.py clone_pod_for_bind — fresh pod/metadata/spec shells,
 * every subtree SHARED with the immutable stored object, the _rr parse
 * cache riding along in the dict copy), set spec.node_name to
 * hostnames[i] and metadata.resource_version to rv_start + i.  The
 * Python loop pays ~6 dict builds + 3 object constructions + 2 attribute
 * stores per pod in interpreted code; here it is a fixed sequence of
 * C-API calls, which is what turns the 50k-pod store pass from the
 * flush's dominant cost into a minor one (docs/design/bind_pipeline.md).
 */
static PyObject *
bind_clone_pods(PyObject *self, PyObject *args)
{
    PyObject *pods, *hosts;
    long long rv_start;
    if (!PyArg_ParseTuple(args, "O!O!L", &PyList_Type, &pods,
                          &PyList_Type, &hosts, &rv_start))
        return NULL;
    Py_ssize_t n = PyList_GET_SIZE(pods);
    if (PyList_GET_SIZE(hosts) != n) {
        PyErr_SetString(PyExc_ValueError, "pods/hostnames length mismatch");
        return NULL;
    }
    PyObject *out = PyList_New(n);
    if (out == NULL)
        return NULL;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *src = PyList_GET_ITEM(pods, i);
        PyObject **sdp = dict_slot(src);
        if (sdp == NULL || *sdp == NULL) {
            if (sdp != NULL)
                PyErr_SetString(PyExc_TypeError, "pod has no __dict__");
            goto fail;
        }
        PyObject *nd = PyDict_Copy(*sdp);
        if (nd == NULL)
            goto fail;
        /* metadata shell with the fresh resource_version */
        PyObject *meta = PyDict_GetItem(nd, s_metadata); /* borrowed */
        PyObject *spec = PyDict_GetItem(nd, s_spec);     /* borrowed */
        PyObject **mdp, **spp;
        if (meta == NULL || spec == NULL ||
            (mdp = dict_slot(meta)) == NULL || *mdp == NULL ||
            (spp = dict_slot(spec)) == NULL || *spp == NULL) {
            if (!PyErr_Occurred())
                PyErr_SetString(PyExc_TypeError,
                                "pod lacks metadata/spec dicts");
            Py_DECREF(nd);
            goto fail;
        }
        PyObject *md = PyDict_Copy(*mdp);
        PyObject *rv = PyLong_FromLongLong(rv_start + (long long)i);
        PyObject *nmeta = NULL;
        if (md == NULL || rv == NULL ||
            PyDict_SetItem(md, s_resource_version, rv) < 0 ||
            (nmeta = adopt_dict(Py_TYPE(meta), md)) == NULL ||
            PyDict_SetItem(nd, s_metadata, nmeta) < 0) {
            Py_XDECREF(nmeta);
            Py_XDECREF(rv);
            Py_XDECREF(md);
            Py_DECREF(nd);
            goto fail;
        }
        Py_DECREF(nmeta);
        Py_DECREF(rv);
        Py_DECREF(md);
        /* spec shell with the bind target */
        PyObject *sd = PyDict_Copy(*spp);
        PyObject *nspec = NULL;
        if (sd == NULL ||
            PyDict_SetItem(sd, s_node_name, PyList_GET_ITEM(hosts, i)) < 0 ||
            (nspec = adopt_dict(Py_TYPE(spec), sd)) == NULL ||
            PyDict_SetItem(nd, s_spec, nspec) < 0) {
            Py_XDECREF(nspec);
            Py_XDECREF(sd);
            Py_DECREF(nd);
            goto fail;
        }
        Py_DECREF(nspec);
        Py_DECREF(sd);
        PyObject *dst = adopt_dict(Py_TYPE(src), nd);
        Py_DECREF(nd);
        if (dst == NULL)
            goto fail;
        PyList_SET_ITEM(out, i, dst);
    }
    return out;
fail:
    Py_DECREF(out);
    return NULL;
}

/* ---- native bind-flush publish + echo (docs/design/bind_pipeline.md) ---- */

static PyObject *s_modified, *s_uid, *s_deletion_timestamp, *s_phase,
    *s_status, *s_task_status_index, *s_tasks, *s_queue, *s_status_version,
    *ph_running, *ph_pending, *ph_succeeded, *ph_failed;

/* publish_shard(objs, infl, kind, shard, news, rv_base)
 *     -> (entries, pairs)
 *
 * The ordered-publish step of one bulk-patch shard in a single call
 * (the Python twin is ObjectStore._install_shard's loop): install
 * news[i] under shard[i]'s key, release the key from the in-flight set,
 * and build both the journal-entry batch [(rv, "MODIFIED", kind, new)]
 * (contiguous reserved rvs from rv_base+1) and the watch-delivery pairs
 * [(old, new)].  Caller holds the store lock; on any failure the caller
 * falls back to the Python loop, which re-applies idempotently. */
static PyObject *
publish_shard(PyObject *self, PyObject *args)
{
    PyObject *objs, *infl, *kind, *shard, *news;
    long long rv_base;
    if (!PyArg_ParseTuple(args, "O!O!UO!O!L", &PyDict_Type, &objs,
                          &PySet_Type, &infl, &kind, &PyList_Type, &shard,
                          &PyList_Type, &news, &rv_base))
        return NULL;
    Py_ssize_t n = PyList_GET_SIZE(shard);
    if (PyList_GET_SIZE(news) != n) {
        PyErr_SetString(PyExc_ValueError, "shard/news length mismatch");
        return NULL;
    }
    PyObject *entries = PyList_New(n);
    PyObject *pairs = PyList_New(n);
    if (entries == NULL || pairs == NULL)
        goto fail;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *item = PyList_GET_ITEM(shard, i);
        if (!PyTuple_Check(item) || PyTuple_GET_SIZE(item) < 2) {
            PyErr_SetString(PyExc_TypeError,
                            "shard items must be (key, old, ...) tuples");
            goto fail;
        }
        PyObject *key = PyTuple_GET_ITEM(item, 0);
        PyObject *old = PyTuple_GET_ITEM(item, 1);
        PyObject *new = PyList_GET_ITEM(news, i);
        if (PyDict_SetItem(objs, key, new) < 0)
            goto fail;
        if (PySet_Discard(infl, key) < 0)
            goto fail;
        PyObject *rv = PyLong_FromLongLong(rv_base + 1 + (long long)i);
        if (rv == NULL)
            goto fail;
        PyObject *entry = PyTuple_New(4);
        if (entry == NULL) {
            Py_DECREF(rv);
            goto fail;
        }
        PyTuple_SET_ITEM(entry, 0, rv);            /* steals rv */
        Py_INCREF(s_modified);
        PyTuple_SET_ITEM(entry, 1, s_modified);
        Py_INCREF(kind);
        PyTuple_SET_ITEM(entry, 2, kind);
        Py_INCREF(new);
        PyTuple_SET_ITEM(entry, 3, new);
        PyList_SET_ITEM(entries, i, entry);
        PyObject *pair = PyTuple_New(2);
        if (pair == NULL)
            goto fail;
        Py_INCREF(old);
        PyTuple_SET_ITEM(pair, 0, old);
        Py_INCREF(new);
        PyTuple_SET_ITEM(pair, 1, new);
        PyList_SET_ITEM(pairs, i, pair);
    }
    return Py_BuildValue("(NN)", entries, pairs);
fail:
    Py_XDECREF(entries);
    Py_XDECREF(pairs);
    return NULL;
}

/* borrowed __dict__ value of a plain-object attribute, or NULL (no
 * exception): obj.__dict__[name] without the descriptor machinery */
static inline PyObject *
dict_attr(PyObject *o, PyObject *name)
{
    PyObject **dp = _PyObject_GetDictPtr(o);
    if (dp == NULL || *dp == NULL)
        return NULL;
    return PyDict_GetItemWithError(*dp, name);   /* borrowed */
}

static inline int
str_eq(PyObject *a, PyObject *b)
{
    if (a == b)
        return 1;
    if (a == NULL || b == NULL)
        return 0;
    if (PyUnicode_Check(a) && PyUnicode_Check(b))
        return PyUnicode_Compare(a, b) == 0 && !PyErr_Occurred();
    return PyObject_RichCompareBool(a, b, Py_EQ) == 1;
}

/* C twin of job_info.get_task_status: pod phase (+ node_name and
 * deletion_timestamp) -> registered TaskStatus member (borrowed ref,
 * NULL when the pod's shape is unexpected — caller falls back) */
static PyObject *
task_status_of(PyObject *pod_dict, PyObject *meta, PyObject *spec)
{
    PyObject *status = PyDict_GetItemWithError(pod_dict, s_status);
    if (status == NULL)
        return NULL;
    PyObject *phase = dict_attr(status, s_phase);
    if (phase == NULL)
        return NULL;
    if (str_eq(phase, ph_running)) {
        PyObject *dt = dict_attr(meta, s_deletion_timestamp);
        return (dt != NULL && dt != Py_None) ? ts_releasing : ts_running;
    }
    if (str_eq(phase, ph_pending)) {
        PyObject *dt = dict_attr(meta, s_deletion_timestamp);
        if (dt != NULL && dt != Py_None)
            return ts_releasing;
        PyObject *nn = dict_attr(spec, s_node_name);
        int truthy = nn == NULL ? 0 : PyObject_IsTrue(nn);
        if (truthy < 0)
            return NULL;
        return truthy ? ts_bound : ts_pending;
    }
    if (str_eq(phase, ph_succeeded))
        return ts_succeeded;
    if (str_eq(phase, ph_failed))
        return ts_failed;
    return ts_unknown;
}

/* slot write with refcount handling */
static inline void
slot_store(PyObject *o, Py_ssize_t off, PyObject *v)
{
    PyObject **p = (PyObject **)((char *)o + off);
    PyObject *old = *p;
    Py_XINCREF(v);
    *p = v;
    Py_XDECREF(old);
}

#define TASK_SLOT(t, off) (*(PyObject **)((char *)(t) + (off)))

/* close one echo-apply run: append (keys, queue) to runs_out for the
 * ledger (only when key collection is on) and release the keys list.
 * run_keys is owned by the caller; consumed here. */
static int
echo_close_run(PyObject *run_job, PyObject **run_keys, PyObject *runs_out)
{
    PyObject *keys = *run_keys;
    *run_keys = NULL;
    if (keys == NULL)
        return 0;
    PyObject *queue = PyObject_GetAttr(run_job, s_queue);
    if (queue == NULL) {
        Py_DECREF(keys);
        return -1;
    }
    PyObject *item = Py_BuildValue("(NN)", keys, queue);  /* steals both */
    if (item == NULL)
        return -1;
    int rc = PyList_Append(runs_out, item);
    Py_DECREF(item);
    return rc;
}

/* one `job._status_version += 1` (per consecutive run, matching the
 * Python path's one move_tasks_status_bulk call per run) */
static int
bump_status_version(PyObject *jd)
{
    PyObject *sv = PyDict_GetItemWithError(jd, s_status_version);
    if (sv == NULL)
        return PyErr_Occurred() ? -1 : 0;
    if (!PyLong_Check(sv))
        return 0;
    PyObject *nv = PyLong_FromLongLong(PyLong_AsLongLong(sv) + 1);
    if (nv == NULL)
        return -1;
    int rc = PyDict_SetItem(jd, s_status_version, nv);
    Py_DECREF(nv);
    return rc;
}

/* bind_echo_apply(pairs, exp, jobs, nodes, want_keys)
 *     -> (runs, rest)
 *
 * The expected-bind-echo ingest of one bulk delivery in a single C pass
 * (the Python twin is the hint branch of update_pods_bulk): for every
 * (old, new) pair whose new.metadata.uid matches the hint map and whose
 * guards hold (node_name == hinted host on both views, both statuses
 * allocated), move the cached task's status index entry old->new, bump
 * the job's status version once per consecutive (job, status) run,
 * refresh the shared pod's resource_version, and sync the node-side
 * stored view.  Both statuses being allocated (and neither Pending)
 * means NO Resource accounting moves — exactly why the Python path used
 * move_tasks_status_bulk, whose per-run bookkeeping this pass inlines.
 *
 * Returns (runs, rest): runs = [(keys, queue)] per run for ONE
 * ledger.confirm_runs call (keys None-skipped when want_keys is false),
 * rest = [(old, new)] pairs that missed a guard, for the Python
 * fallback loop.  Caller holds the cache mutex. */
static PyObject *
bind_echo_apply(PyObject *self, PyObject *args)
{
    PyObject *pairs, *exp, *jobs, *nodes;
    int want_keys;
    if (!PyArg_ParseTuple(args, "O!O!O!O!p", &PyList_Type, &pairs,
                          &PyDict_Type, &exp, &PyDict_Type, &jobs,
                          &PyDict_Type, &nodes, &want_keys))
        return NULL;
    if (task_type == NULL || ts_allocated_set == NULL) {
        PyErr_SetString(PyExc_RuntimeError,
                        "task type/status members not registered");
        return NULL;
    }
    PyObject *runs_out = PyList_New(0);
    PyObject *rest = PyList_New(0);
    PyObject *run_job = NULL;       /* borrowed */
    PyObject *run_status = NULL;    /* borrowed */
    PyObject *run_keys = NULL;      /* owned, alive while run open */
    if (runs_out == NULL || rest == NULL)
        goto fail;
    /* cache the last node lookup: hosts repeat ~5x in a burst */
    PyObject *last_host = NULL, *last_node_tasks = NULL; /* borrowed */
    Py_ssize_t n = PyList_GET_SIZE(pairs);
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *pair = PyList_GET_ITEM(pairs, i);
        if (!PyTuple_Check(pair) || PyTuple_GET_SIZE(pair) != 2) {
            PyErr_SetString(PyExc_TypeError, "pairs items must be 2-tuples");
            goto fail;
        }
        PyObject *new = PyTuple_GET_ITEM(pair, 1);
        PyObject **ndp = _PyObject_GetDictPtr(new);
        PyObject *hint = NULL;
        PyObject *meta = NULL, *spec = NULL;
        if (ndp != NULL && *ndp != NULL) {
            meta = PyDict_GetItemWithError(*ndp, s_metadata);
            spec = PyDict_GetItemWithError(*ndp, s_spec);
            if (meta != NULL && spec != NULL) {
                PyObject *uid = dict_attr(meta, s_uid);
                if (uid != NULL)
                    hint = PyDict_GetItemWithError(exp, uid);
            }
        }
        if (PyErr_Occurred())
            goto fail;
        PyObject *task = NULL, *host = NULL, *job = NULL;
        PyObject *new_status = NULL, *old_status = NULL;
        if (hint != NULL && PyTuple_Check(hint)
                && PyTuple_GET_SIZE(hint) == 2) {
            task = PyTuple_GET_ITEM(hint, 0);
            host = PyTuple_GET_ITEM(hint, 1);
        }
        /* guards — any miss sends the pair to the Python fallback (the
         * same chain the Python hint branch evaluates, in order) */
        if (task != NULL && Py_TYPE(task) == task_type) {
            PyObject *nn = dict_attr(spec, s_node_name);
            old_status = TASK_SLOT(task, status_offset);
            if (str_eq(nn, host)
                    && str_eq(TASK_SLOT(task, t_node_name_off), host)
                    && old_status != NULL
                    && PySet_Contains(ts_allocated_set, old_status) == 1) {
                new_status = task_status_of(*ndp, meta, spec);
                if (new_status != NULL
                        && PySet_Contains(ts_allocated_set,
                                          new_status) == 1) {
                    PyObject *jid = TASK_SLOT(task, t_job_off);
                    if (jid != NULL)
                        job = PyDict_GetItemWithError(jobs, jid);
                }
            }
            if (PyErr_Occurred())
                goto fail;
        }
        PyObject **jdp = job == NULL ? NULL : _PyObject_GetDictPtr(job);
        if (jdp == NULL || *jdp == NULL) {
            if (run_job != NULL) {
                if (echo_close_run(run_job, &run_keys, runs_out) < 0)
                    goto fail;
                run_job = NULL;
            }
            if (PyList_Append(rest, pair) < 0)
                goto fail;
            continue;
        }
        PyObject *jd = *jdp;
        if (job != run_job || new_status != run_status) {
            if (run_job != NULL
                    && echo_close_run(run_job, &run_keys, runs_out) < 0)
                goto fail;
            run_job = job;
            run_status = new_status;
            if (want_keys) {
                run_keys = PyList_New(0);
                if (run_keys == NULL)
                    goto fail;
            }
            if (bump_status_version(jd) < 0)
                goto fail;
        }
        /* status-index move old->new (the move_tasks_status_bulk body
         * for the no-Resource-flip case: both statuses allocated) */
        PyObject *uid = TASK_SLOT(task, uid_offset);
        PyObject *tsi = PyDict_GetItemWithError(jd, s_task_status_index);
        PyObject *jtasks = PyDict_GetItemWithError(jd, s_tasks);
        if (uid == NULL || tsi == NULL || !PyDict_Check(tsi)
                || jtasks == NULL || !PyDict_Check(jtasks)) {
            if (PyErr_Occurred())
                goto fail;
            PyErr_SetString(PyExc_TypeError, "job lacks task index dicts");
            goto fail;
        }
        PyObject *old_idx = PyDict_GetItemWithError(tsi, old_status);
        if (old_idx != NULL && PyDict_Check(old_idx)) {
            if (PyDict_DelItem(old_idx, uid) < 0)
                PyErr_Clear();                     /* pop(uid, None) */
            if (PyDict_GET_SIZE(old_idx) == 0 && old_status != new_status
                    && PyDict_DelItem(tsi, old_status) < 0)
                PyErr_Clear();
        } else if (PyErr_Occurred())
            goto fail;
        PyObject *new_idx = PyDict_GetItemWithError(tsi, new_status);
        if (new_idx == NULL) {
            if (PyErr_Occurred())
                goto fail;
            new_idx = PyDict_New();       /* defaultdict(dict) materialize */
            if (new_idx == NULL
                    || PyDict_SetItem(tsi, new_status, new_idx) < 0) {
                Py_XDECREF(new_idx);
                goto fail;
            }
            Py_DECREF(new_idx);           /* tsi holds it; borrowed now */
        }
        slot_store(task, status_offset, new_status);
        if (PyDict_SetItem(jtasks, uid, task) < 0
                || PyDict_SetItem(new_idx, uid, task) < 0)
            goto fail;
        /* shared pod picks up the committed resource_version */
        PyObject *rv = dict_attr(meta, s_resource_version);
        PyObject *pod = TASK_SLOT(task, t_pod_off);
        if (rv != NULL && pod != NULL) {
            PyObject *pmeta = dict_attr(pod, s_metadata);
            PyObject **pmd = pmeta == NULL ? NULL
                : _PyObject_GetDictPtr(pmeta);
            if (pmd != NULL && *pmd != NULL
                    && PyDict_SetItem(*pmd, s_resource_version, rv) < 0)
                goto fail;
        }
        /* "ns/name" key (precomputed TaskInfo slot): the node-side
         * view lookup and the ledger both want it */
        PyObject *key = TASK_SLOT(task, t_key_off);
        if (key == NULL) {
            PyErr_SetString(PyExc_TypeError, "task lacks key_cache");
            goto fail;
        }
        Py_INCREF(key);
        if (run_keys != NULL && PyList_Append(run_keys, key) < 0) {
            Py_DECREF(key);
            goto fail;
        }
        if (host != last_host) {
            PyObject *node = PyDict_GetItemWithError(nodes, host);
            if (node == NULL && PyErr_Occurred()) {
                Py_DECREF(key);
                goto fail;
            }
            last_node_tasks = node == NULL ? NULL
                : dict_attr(node, s_tasks);
            last_host = host;
        }
        if (last_node_tasks != NULL && PyDict_Check(last_node_tasks)) {
            PyObject *stored = PyDict_GetItemWithError(last_node_tasks,
                                                       key);
            if (stored == NULL && PyErr_Occurred()) {
                Py_DECREF(key);
                goto fail;
            }
            if (stored != NULL && stored != task
                    && Py_TYPE(stored) == task_type) {
                slot_store(stored, status_offset, new_status);
                PyObject *spod = TASK_SLOT(stored, t_pod_off);
                if (spod != NULL && spod != pod && rv != NULL) {
                    PyObject *smeta = dict_attr(spod, s_metadata);
                    PyObject **smd = smeta == NULL ? NULL
                        : _PyObject_GetDictPtr(smeta);
                    if (smd != NULL && *smd != NULL
                            && PyDict_SetItem(*smd, s_resource_version,
                                              rv) < 0) {
                        Py_DECREF(key);
                        goto fail;
                    }
                }
            }
        }
        Py_DECREF(key);
    }
    if (run_job != NULL
            && echo_close_run(run_job, &run_keys, runs_out) < 0)
        goto fail;
    return Py_BuildValue("(NN)", runs_out, rest);
fail:
    Py_XDECREF(runs_out);
    Py_XDECREF(rest);
    Py_XDECREF(run_keys);
    return NULL;
}

/* ---- native lifecycle-ledger completion (trace/ledger.confirm_runs:
 * the 50k-per-flush bind-echo completion loop) ---- */

static PyTypeObject *entry_type = NULL, *agg_type = NULL;
static Py_ssize_t e_stamps_off = -1, e_detours_off = -1, e_trace_off = -1,
    e_queue_off = -1;
static Py_ssize_t a_count_off = -1, a_total_off = -1, a_samples_off = -1;
static PyObject *hop_table = NULL;   /* ledger._HOP_NAME (list of lists) */
static long commit_idx = -1, echo_idx = -1;
static PyObject *s_append, *s_hop, *s_queue_label;

/* register_ledger_types(_Entry, _Agg, hop_table, commit_idx, echo_idx) */
static PyObject *
register_ledger_types(PyObject *self, PyObject *args)
{
    PyObject *etp, *atp, *table;
    long ci, ei;
    if (!PyArg_ParseTuple(args, "OOO!ll", &etp, &atp, &PyList_Type,
                          &table, &ci, &ei))
        return NULL;
    if (!PyType_Check(etp) || !PyType_Check(atp)) {
        PyErr_SetString(PyExc_TypeError, "expected types");
        return NULL;
    }
    Py_ssize_t so = member_offset((PyTypeObject *)etp, "stamps");
    Py_ssize_t dto = member_offset((PyTypeObject *)etp, "detours");
    Py_ssize_t tro = member_offset((PyTypeObject *)etp, "trace");
    Py_ssize_t qo = member_offset((PyTypeObject *)etp, "queue");
    Py_ssize_t co = member_offset((PyTypeObject *)atp, "count");
    Py_ssize_t to = member_offset((PyTypeObject *)atp, "total");
    Py_ssize_t smo = member_offset((PyTypeObject *)atp, "samples");
    if (so < 0 || dto < 0 || tro < 0 || qo < 0 || co < 0 || to < 0
            || smo < 0)
        return NULL;
    e_stamps_off = so; e_detours_off = dto; e_trace_off = tro;
    e_queue_off = qo;
    a_count_off = co; a_total_off = to; a_samples_off = smo;
    Py_INCREF(etp);
    Py_XDECREF((PyObject *)entry_type);
    entry_type = (PyTypeObject *)etp;
    Py_INCREF(atp);
    Py_XDECREF((PyObject *)agg_type);
    agg_type = (PyTypeObject *)atp;
    Py_INCREF(table);
    Py_XDECREF(hop_table);
    hop_table = table;
    commit_idx = ci;
    echo_idx = ei;
    Py_RETURN_NONE;
}

/* one aggregate sink: the _Agg plus its cached deque-append bound
 * method and its staged-export list */
typedef struct {
    PyObject *agg;      /* borrowed (held by _hops/_queue_e2e) */
    PyObject *append;   /* owned bound method */
    PyObject *exports;  /* borrowed (held by _pending_exports) */
} sink_t;

/* agg.count += 1; agg.total += ms; agg.samples.append(ms);
 * exports.append(ms) — the exact _Agg.add + export staging sequence */
static int
sink_add(sink_t *s, double ms)
{
    PyObject **cp = (PyObject **)((char *)s->agg + a_count_off);
    PyObject *nv = PyLong_FromLongLong(PyLong_AsLongLong(*cp) + 1);
    if (nv == NULL)
        return -1;
    Py_DECREF(*cp);
    *cp = nv;
    PyObject **tp = (PyObject **)((char *)s->agg + a_total_off);
    nv = PyFloat_FromDouble(PyFloat_AS_DOUBLE(*tp) + ms);
    if (nv == NULL)
        return -1;
    Py_DECREF(*tp);
    *tp = nv;
    PyObject *msv = PyFloat_FromDouble(ms);
    if (msv == NULL)
        return -1;
    PyObject *r = PyObject_CallOneArg(s->append, msv);
    if (r == NULL) {
        Py_DECREF(msv);
        return -1;
    }
    Py_DECREF(r);
    int rc = 0;
    if (s->exports != NULL)
        rc = PyList_Append(s->exports, msv);
    Py_DECREF(msv);
    return rc;
}

/* resolve (or create) the _Agg in aggs[name] and its export list in
 * pending[_export_keys[label_key]]; label_kind/label_value build the
 * export key tuple (metric, ((label_kind, label_value),)). */
static int
sink_resolve(sink_t *s, PyObject *aggs, PyObject *name, PyObject *pending,
             PyObject *ekeys, PyObject *metric, PyObject *label_kind,
             PyObject *label_value, PyObject *ekey_probe)
{
    s->agg = NULL;
    s->append = NULL;
    s->exports = NULL;
    PyObject *agg = PyDict_GetItemWithError(aggs, name);
    if (agg == NULL) {
        if (PyErr_Occurred())
            return -1;
        agg = PyObject_CallNoArgs((PyObject *)agg_type);
        if (agg == NULL || PyDict_SetItem(aggs, name, agg) < 0) {
            Py_XDECREF(agg);
            return -1;
        }
        Py_DECREF(agg);   /* aggs holds it */
    }
    if (Py_TYPE(agg) != agg_type) {
        PyErr_SetString(PyExc_TypeError, "unexpected aggregate type");
        return -1;
    }
    s->agg = agg;
    PyObject *samples = *(PyObject **)((char *)agg + a_samples_off);
    if (samples == NULL) {
        PyErr_SetString(PyExc_TypeError, "aggregate lacks samples");
        return -1;
    }
    s->append = PyObject_GetAttr(samples, s_append);
    if (s->append == NULL)
        return -1;
    if (pending == NULL)
        return 0;   /* exports disabled (no metric plumbed) */
    PyObject *ek = PyDict_GetItemWithError(ekeys, ekey_probe);
    if (ek == NULL) {
        if (PyErr_Occurred())
            return -1;
        PyObject *label = Py_BuildValue("((OO))", label_kind, label_value);
        if (label == NULL)
            return -1;
        ek = Py_BuildValue("(ON)", metric, label);
        if (ek == NULL || PyDict_SetItem(ekeys, ekey_probe, ek) < 0) {
            Py_XDECREF(ek);
            return -1;
        }
        Py_DECREF(ek);
        ek = PyDict_GetItem(ekeys, ekey_probe);
    }
    PyObject *lst = PyDict_GetItemWithError(pending, ek);
    if (lst == NULL) {
        if (PyErr_Occurred())
            return -1;
        lst = PyList_New(0);
        if (lst == NULL || PyDict_SetItem(pending, ek, lst) < 0) {
            Py_XDECREF(lst);
            return -1;
        }
        Py_DECREF(lst);
        lst = PyDict_GetItem(pending, ek);
    }
    s->exports = lst;
    return 0;
}

/* ledger_confirm_runs(entries, hops, queue_e2e, pending, ekeys, recent,
 *                     hop_metric, e2e_metric, runs, commit_t, echo_t)
 *     -> completed count
 *
 * The bind-echo completion loop of trace/ledger.confirm_runs in C:
 * for every key still open, stamp store_committed @commit_t (unless
 * already stamped) and echo_confirmed @echo_t, aggregate every hop +
 * the e2e into the hop/queue aggregates, stage the prometheus exports
 * and the recent-completions ring entry, and retire the entry. The
 * caller holds the ledger lock; arithmetic is the exact per-pod
 * sequence of the Python loop (fingerprints must not see which ran). */
static PyObject *
ledger_confirm_runs(PyObject *self, PyObject *args)
{
    PyObject *entries, *hops, *queue_e2e, *pending, *ekeys, *recent;
    PyObject *hop_metric, *e2e_metric, *runs;
    double commit_t, echo_t;
    if (!PyArg_ParseTuple(args, "O!O!O!O!O!OOOO!dd",
                          &PyDict_Type, &entries, &PyDict_Type, &hops,
                          &PyDict_Type, &queue_e2e, &PyDict_Type, &pending,
                          &PyDict_Type, &ekeys, &recent,
                          &hop_metric, &e2e_metric, &PyList_Type, &runs,
                          &commit_t, &echo_t))
        return NULL;
    if (entry_type == NULL || agg_type == NULL || hop_table == NULL) {
        PyErr_SetString(PyExc_RuntimeError, "ledger types not registered");
        return NULL;
    }
    long completed = 0;
    PyObject *recent_append = PyObject_GetAttr(recent, s_append);
    if (recent_append == NULL)
        return NULL;
    /* per-call sink caches: hop name -> sink, plus the e2e + queue
     * sinks (queue constant per run) */
    PyObject *sink_keys = PyList_New(0);   /* keeps append refs alive */
    /* 7 stages -> at most 21 distinct hop names; 24 is unreachable */
    sink_t hop_sinks[24];
    PyObject *hop_names[24];
    int n_hop_sinks = 0;
    sink_t e2e_sink = {NULL, NULL, NULL};
    PyObject *e2e_name = PyUnicode_InternFromString("e2e");
    if (sink_keys == NULL || e2e_name == NULL)
        goto fail;
    if (sink_resolve(&e2e_sink, hops, e2e_name, NULL, NULL, NULL, NULL,
                     NULL, NULL) < 0)
        goto fail;
    if (PyList_Append(sink_keys, e2e_sink.append) < 0) {
        Py_DECREF(e2e_sink.append);
        goto fail;
    }
    Py_DECREF(e2e_sink.append);   /* sink_keys holds it */
    Py_ssize_t nr = PyList_GET_SIZE(runs);
    for (Py_ssize_t r = 0; r < nr; r++) {
        PyObject *run = PyList_GET_ITEM(runs, r);
        if (!PyTuple_Check(run) || PyTuple_GET_SIZE(run) != 2) {
            PyErr_SetString(PyExc_TypeError, "runs items must be 2-tuples");
            goto fail;
        }
        PyObject *keys = PyTuple_GET_ITEM(run, 0);
        PyObject *queue = PyTuple_GET_ITEM(run, 1);
        if (!PyList_Check(keys)) {
            PyErr_SetString(PyExc_TypeError, "run keys must be a list");
            goto fail;
        }
        PyObject *q = (queue == Py_None || queue == NULL)
            ? PyUnicode_InternFromString("") : (Py_INCREF(queue), queue);
        if (q == NULL)
            goto fail;
        sink_t q_sink;
        PyObject *probe = Py_BuildValue("(sO)", "q", q);
        if (probe == NULL) {
            Py_DECREF(q);
            goto fail;
        }
        int rc = sink_resolve(&q_sink, queue_e2e, q, pending, ekeys,
                              e2e_metric, s_queue_label, q, probe);
        Py_DECREF(probe);
        if (rc < 0) {
            Py_DECREF(q);
            goto fail;
        }
        if (PyList_Append(sink_keys, q_sink.append) < 0) {
            Py_DECREF(q);
            Py_DECREF(q_sink.append);
            goto fail;
        }
        Py_DECREF(q_sink.append);   /* sink_keys holds it */
        Py_ssize_t nk = PyList_GET_SIZE(keys);
        for (Py_ssize_t ki = 0; ki < nk; ki++) {
            PyObject *key = PyList_GET_ITEM(keys, ki);
            PyObject *e = PyDict_GetItemWithError(entries, key);
            if (e == NULL) {
                if (PyErr_Occurred()) {
                    Py_DECREF(q);
                    goto fail;
                }
                continue;
            }
            if (Py_TYPE(e) != entry_type) {
                Py_DECREF(q);
                PyErr_SetString(PyExc_TypeError, "unexpected entry type");
                goto fail;
            }
            PyObject *stamps = *(PyObject **)((char *)e + e_stamps_off);
            if (stamps == NULL || !PyList_Check(stamps)) {
                Py_DECREF(q);
                PyErr_SetString(PyExc_TypeError, "entry lacks stamps");
                goto fail;
            }
            Py_ssize_t ns = PyList_GET_SIZE(stamps);
            long last_i = -1;
            double last_t = 0.0;
            if (ns > 0) {
                PyObject *last = PyList_GET_ITEM(stamps, ns - 1);
                last_i = PyLong_AsLong(PyTuple_GET_ITEM(last, 0));
                last_t = PyFloat_AsDouble(PyTuple_GET_ITEM(last, 1));
                if (PyErr_Occurred()) {
                    Py_DECREF(q);
                    goto fail;
                }
            }
            if (last_i >= echo_idx)
                continue;
            if (queue != Py_None && queue != NULL)
                slot_store(e, e_queue_off, queue);
            /* the virtual commit/echo stamps (appended by the Python
             * loop; computed in place here) */
            double tc = 0.0;
            int have_commit = 0;
            if (last_i < commit_idx) {
                tc = commit_t >= last_t ? commit_t : last_t;
                have_commit = 1;
            }
            double base = have_commit ? tc : last_t;
            double te = echo_t >= base ? echo_t : base;
            double t0 = ns > 0
                ? PyFloat_AsDouble(PyTuple_GET_ITEM(
                      PyList_GET_ITEM(stamps, 0), 1))
                : (have_commit ? tc : te);
            if (PyErr_Occurred()) {
                Py_DECREF(q);
                goto fail;
            }
            double e2e_ms = (te - t0) * 1000.0;
            PyObject *hop_list = PyList_New(0);
            if (hop_list == NULL) {
                Py_DECREF(q);
                goto fail;
            }
            /* walk: existing stamp pairs, then ->commit, then ->echo */
            long prev_i = -1;
            double prev_t = 0.0;
            int first = 1;
            int ok = 1;
            for (Py_ssize_t si = 0; ok && si <= ns + 1; si++) {
                long i1;
                double t1;
                if (si < ns) {
                    PyObject *st = PyList_GET_ITEM(stamps, si);
                    i1 = PyLong_AsLong(PyTuple_GET_ITEM(st, 0));
                    t1 = PyFloat_AsDouble(PyTuple_GET_ITEM(st, 1));
                    if (PyErr_Occurred()) {
                        ok = 0;
                        break;
                    }
                } else if (si == ns) {
                    if (!have_commit)
                        continue;
                    i1 = commit_idx;
                    t1 = tc;
                } else {
                    i1 = echo_idx;
                    t1 = te;
                }
                if (first) {
                    first = 0;
                    prev_i = i1;
                    prev_t = t1;
                    continue;
                }
                PyObject *hop = PyList_GET_ITEM(
                    PyList_GET_ITEM(hop_table, prev_i), i1);
                double ms = (t1 - prev_t) * 1000.0;
                prev_i = i1;
                prev_t = t1;
                sink_t *hs = NULL;
                for (int h = 0; h < n_hop_sinks; h++)
                    if (hop_names[h] == hop) {
                        hs = &hop_sinks[h];
                        break;
                    }
                if (hs == NULL) {
                    if (n_hop_sinks >= 24) {
                        PyErr_SetString(PyExc_RuntimeError,
                                        "too many hop kinds");
                        ok = 0;
                        break;
                    }
                    hs = &hop_sinks[n_hop_sinks];
                    if (sink_resolve(hs, hops, hop, pending, ekeys,
                                     hop_metric, s_hop, hop, hop) < 0) {
                        ok = 0;
                        break;
                    }
                    if (PyList_Append(sink_keys, hs->append) < 0) {
                        Py_DECREF(hs->append);
                        ok = 0;
                        break;
                    }
                    Py_DECREF(hs->append);
                    hop_names[n_hop_sinks++] = hop;
                }
                PyObject *pair = Py_BuildValue("(Od)", hop, ms);
                if (pair == NULL || PyList_Append(hop_list, pair) < 0) {
                    Py_XDECREF(pair);
                    ok = 0;
                    break;
                }
                Py_DECREF(pair);
                if (sink_add(hs, ms) < 0) {
                    ok = 0;
                    break;
                }
            }
            if (!ok) {
                Py_DECREF(hop_list);
                Py_DECREF(q);
                goto fail;
            }
            if (sink_add(&e2e_sink, e2e_ms) < 0
                    || sink_add(&q_sink, e2e_ms) < 0) {
                Py_DECREF(hop_list);
                Py_DECREF(q);
                goto fail;
            }
            PyObject *trace = *(PyObject **)((char *)e + e_trace_off);
            PyObject *detours = *(PyObject **)((char *)e + e_detours_off);
            PyObject *rec = Py_BuildValue(
                "(OOOdOO)", key, trace == NULL ? Py_None : trace, q,
                e2e_ms, hop_list,
                detours == NULL ? Py_None : detours);
            Py_DECREF(hop_list);
            if (rec == NULL) {
                Py_DECREF(q);
                goto fail;
            }
            PyObject *rr = PyObject_CallOneArg(recent_append, rec);
            Py_DECREF(rec);
            if (rr == NULL) {
                Py_DECREF(q);
                goto fail;
            }
            Py_DECREF(rr);
            if (PyDict_DelItem(entries, key) < 0) {
                Py_DECREF(q);
                goto fail;
            }
            completed++;
        }
        Py_DECREF(q);
    }
    Py_DECREF(recent_append);
    Py_DECREF(sink_keys);
    Py_XDECREF(e2e_name);
    return PyLong_FromLong(completed);
fail:
    Py_DECREF(recent_append);
    Py_XDECREF(sink_keys);
    Py_XDECREF(e2e_name);
    return NULL;
}

/* ---- native bind APPLY (the _BindBurst status-move + node accounting
 * pass of cache._apply_bind_bursts, docs/design/bind_pipeline.md) ---- */

static PyObject *s_pairs, *s_accepted, *s_bound, *s_idle, *s_used,
    *s_name, *s_node, *s_gpu_devices, *s_allocated, *s_pending_request,
    *s_namespace_str;

#define RES_DBL(r, off) PyFloat_AS_DOUBLE(*(PyObject **)((char *)(r) + (off)))
#define RES_OBJ(r, off) (*(PyObject **)((char *)(r) + (off)))

static inline int
le_eps(double l, double r, double eps)
{
    return l < r || fabs(l - r) < eps;
}

/* accumulate src (a Resource.scalars dict) into *accp, creating the
 * accumulator dict lazily — the C twin of Resource.add's scalar loop
 * against a fresh Resource (same name insertion order, same float-add
 * order) */
static int
acc_scalars(PyObject **accp, PyObject *src)
{
    if (src == NULL || !PyDict_Check(src) || PyDict_GET_SIZE(src) == 0)
        return 0;
    if (*accp == NULL) {
        *accp = PyDict_New();
        if (*accp == NULL)
            return -1;
    }
    Py_ssize_t pos = 0;
    PyObject *name, *val;
    while (PyDict_Next(src, &pos, &name, &val)) {
        if (!PyFloat_Check(val))
            return -2;   /* unexpected shape: caller falls back */
        PyObject *cur = PyDict_GetItemWithError(*accp, name);
        if (cur == NULL && PyErr_Occurred())
            return -1;
        double d = (cur == NULL ? 0.0 : PyFloat_AS_DOUBLE(cur))
            + PyFloat_AS_DOUBLE(val);
        PyObject *nv = PyFloat_FromDouble(d);
        if (nv == NULL || PyDict_SetItem(*accp, name, nv) < 0) {
            Py_XDECREF(nv);
            return -1;
        }
        Py_DECREF(nv);
    }
    return 0;
}

/* acc_scalars for the mutation phase, where validation already proved
 * every scalars dict float-valued: any failure is a real error */
static int
acc_scalars_strict(PyObject **accp, PyObject *src)
{
    int rc = acc_scalars(accp, src);
    if (rc == -2)
        PyErr_SetString(PyExc_TypeError, "non-float resource scalar");
    return rc ? -1 : 0;
}

/* total(acc) <= res within EPS under Zero defaults — the C twin of
 * Resource.less_equal(res, ZERO) for an accumulated (tcpu, tmem, tsc)
 * left side. 1 yes, 0 no, -1 error. */
static int
le_eps_resource(double tcpu, double tmem, PyObject *tsc, PyObject *res,
                double eps)
{
    if (!le_eps(tcpu, RES_DBL(res, res_cpu_offset), eps)
            || !le_eps(tmem, RES_DBL(res, res_mem_offset), eps))
        return 0;
    PyObject *rsc = RES_OBJ(res, res_scalars_offset);
    int t_empty = tsc == NULL || PyDict_GET_SIZE(tsc) == 0;
    int r_empty = rsc == NULL || !PyDict_Check(rsc)
        || PyDict_GET_SIZE(rsc) == 0;
    if (t_empty && r_empty)
        return 1;
    Py_ssize_t pos = 0;
    PyObject *name, *val;
    if (!t_empty) {
        while (PyDict_Next(tsc, &pos, &name, &val)) {
            double l = PyFloat_AS_DOUBLE(val);
            PyObject *rv = r_empty ? NULL
                : PyDict_GetItemWithError(rsc, name);
            if (rv == NULL && PyErr_Occurred())
                return -1;
            double r = rv == NULL ? 0.0
                : (PyFloat_Check(rv) ? PyFloat_AS_DOUBLE(rv) : -1.0);
            if (rv != NULL && !PyFloat_Check(rv))
                return -1;
            if (isinf(r) && r > 0)
                continue;
            if ((isinf(l) && l > 0) || !le_eps(l, r, eps))
                return 0;
        }
    }
    if (!r_empty) {
        pos = 0;
        while (PyDict_Next(rsc, &pos, &name, &val)) {
            if (!t_empty) {
                PyObject *lv = PyDict_GetItemWithError(tsc, name);
                if (lv == NULL && PyErr_Occurred())
                    return -1;
                if (lv != NULL)
                    continue;   /* already compared above */
            }
            if (!PyFloat_Check(val))
                return -1;
            double r = PyFloat_AS_DOUBLE(val);
            if (isinf(r) && r > 0)
                continue;
            if (!le_eps(0.0, r, eps))
                return 0;
        }
    }
    return 1;
}

/* res.milli_cpu/memory += (or -=) the accumulated deltas; scalars follow
 * Resource.add's (always iterate rr) / sub_unchecked's (skip when self
 * empty) semantics via the add_semantics flag */
static int
apply_res_delta(PyObject *res, double dcpu, double dmem, PyObject *dsc,
                int sign, int add_semantics)
{
    PyObject *nv = PyFloat_FromDouble(
        RES_DBL(res, res_cpu_offset) + sign * dcpu);
    if (nv == NULL)
        return -1;
    PyObject **slot = (PyObject **)((char *)res + res_cpu_offset);
    Py_DECREF(*slot);
    *slot = nv;
    nv = PyFloat_FromDouble(RES_DBL(res, res_mem_offset) + sign * dmem);
    if (nv == NULL)
        return -1;
    slot = (PyObject **)((char *)res + res_mem_offset);
    Py_DECREF(*slot);
    *slot = nv;
    if (dsc == NULL || PyDict_GET_SIZE(dsc) == 0)
        return 0;
    PyObject *rsc = RES_OBJ(res, res_scalars_offset);
    if (rsc == NULL || !PyDict_Check(rsc))
        return -1;
    if (!add_semantics && PyDict_GET_SIZE(rsc) == 0)
        return 0;   /* sub_unchecked: `if not self.scalars: return` */
    Py_ssize_t pos = 0;
    PyObject *name, *val;
    while (PyDict_Next(dsc, &pos, &name, &val)) {
        PyObject *cur = PyDict_GetItemWithError(rsc, name);
        if (cur == NULL && PyErr_Occurred())
            return -1;
        double d = (cur == NULL ? 0.0 : PyFloat_AS_DOUBLE(cur))
            + sign * PyFloat_AS_DOUBLE(val);
        nv = PyFloat_FromDouble(d);
        if (nv == NULL || PyDict_SetItem(rsc, name, nv) < 0) {
            Py_XDECREF(nv);
            return -1;
        }
        Py_DECREF(nv);
    }
    return 0;
}

/* bind_apply_bursts(bursts, jobs, nodes, dirty_jobs, dirty_nodes,
 *                   binding, eps) -> bool
 *
 * The coalesced cross-gang bind apply in one C pass: group every
 * burst's (task_info, hostname) pairs by job, move the cached tasks to
 * Binding (status-index move + allocated/pending_request flips, one
 * status-version bump per job), then run ONE accounting pass per node
 * (idle/used update + task clone install) and populate each burst's
 * accepted/bound lists in (job-group, node-group) order — exactly the
 * Python _apply_bind_bursts sequence.
 *
 * All-or-nothing: a full validation pass runs FIRST (missing job/task/
 * node, node-name conflicts, duplicate keys, idle fit, GPU-sharing
 * nodes, unexpected shapes) and returns False with NOTHING mutated —
 * the caller then takes the Python path, which handles every irregular
 * case with its per-task fallback semantics. */
static PyObject *
bind_apply_bursts(PyObject *self, PyObject *args)
{
    PyObject *bursts, *jobs, *nodes, *dirty_jobs, *dirty_nodes, *binding;
    double eps;
    if (!PyArg_ParseTuple(args, "O!O!O!O!O!Od", &PyList_Type, &bursts,
                          &PyDict_Type, &jobs, &PyDict_Type, &nodes,
                          &PySet_Type, &dirty_jobs, &PySet_Type,
                          &dirty_nodes, &binding, &eps))
        return NULL;
    if (task_type == NULL || res_type == NULL || ts_allocated_set == NULL) {
        PyErr_SetString(PyExc_RuntimeError, "types not registered");
        return NULL;
    }
    PyObject *by_job = PyDict_New();    /* jid -> [(burst, ti, stored)] */
    PyObject *by_node = PyDict_New();   /* host -> [(burst, ti, stored)] */
    if (by_job == NULL || by_node == NULL)
        goto err;

    /* ---- grouping ---- */
    Py_ssize_t nb = PyList_GET_SIZE(bursts);
    for (Py_ssize_t b = 0; b < nb; b++) {
        PyObject *burst = PyList_GET_ITEM(bursts, b);
        PyObject *bpairs = PyObject_GetAttr(burst, s_pairs);
        if (bpairs == NULL || !PyList_Check(bpairs)) {
            Py_XDECREF(bpairs);
            goto fallback;
        }
        Py_ssize_t np = PyList_GET_SIZE(bpairs);
        for (Py_ssize_t i = 0; i < np; i++) {
            PyObject *pr = PyList_GET_ITEM(bpairs, i);
            if (!PyTuple_Check(pr) || PyTuple_GET_SIZE(pr) != 2) {
                Py_DECREF(bpairs);
                goto fallback;
            }
            PyObject *ti = PyTuple_GET_ITEM(pr, 0);
            PyObject *host = PyTuple_GET_ITEM(pr, 1);
            if (Py_TYPE(ti) != task_type) {
                Py_DECREF(bpairs);
                goto fallback;
            }
            PyObject *jid = TASK_SLOT(ti, t_job_off);
            PyObject *lst = PyDict_GetItemWithError(by_job, jid);
            if (lst == NULL) {
                if (PyErr_Occurred()) {
                    Py_DECREF(bpairs);
                    goto err;
                }
                lst = PyList_New(0);
                if (lst == NULL
                        || PyDict_SetItem(by_job, jid, lst) < 0) {
                    Py_XDECREF(lst);
                    Py_DECREF(bpairs);
                    goto err;
                }
                Py_DECREF(lst);
            }
            PyObject *item = PyTuple_Pack(3, burst, ti, host);
            if (item == NULL || PyList_Append(lst, item) < 0) {
                Py_XDECREF(item);
                Py_DECREF(bpairs);
                goto err;
            }
            Py_DECREF(item);
        }
        Py_DECREF(bpairs);
    }

    /* ---- validation: resolve stored tasks + nodes, build by_node ---- */
    Py_ssize_t jpos = 0;
    PyObject *jid, *items;
    while (PyDict_Next(by_job, &jpos, &jid, &items)) {
        PyObject *job = PyDict_GetItemWithError(jobs, jid);
        if (job == NULL) {
            if (PyErr_Occurred())
                goto err;
            goto fallback;
        }
        PyObject **jdp = _PyObject_GetDictPtr(job);
        if (jdp == NULL || *jdp == NULL)
            goto fallback;
        PyObject *jtasks = PyDict_GetItemWithError(*jdp, s_tasks);
        PyObject *alloc = PyDict_GetItemWithError(*jdp, s_allocated);
        PyObject *pend = PyDict_GetItemWithError(*jdp, s_pending_request);
        PyObject *vtsi = PyDict_GetItemWithError(*jdp, s_task_status_index);
        if (jtasks == NULL || !PyDict_Check(jtasks) || alloc == NULL
                || Py_TYPE(alloc) != res_type || pend == NULL
                || Py_TYPE(pend) != res_type || vtsi == NULL
                || !PyDict_Check(vtsi)) {
            if (PyErr_Occurred())
                goto err;
            goto fallback;
        }
        double p_cpu = 0.0, p_mem = 0.0;
        PyObject *p_sc = NULL;
        int p_any = 0;
        Py_ssize_t ni = PyList_GET_SIZE(items);
        for (Py_ssize_t i = 0; i < ni; i++) {
            PyObject *item = PyList_GET_ITEM(items, i);
            PyObject *ti = PyTuple_GET_ITEM(item, 1);
            PyObject *host = PyTuple_GET_ITEM(item, 2);
            PyObject *stored = PyDict_GetItemWithError(
                jtasks, TASK_SLOT(ti, uid_offset));
            if (stored == NULL || Py_TYPE(stored) != task_type) {
                Py_XDECREF(p_sc);
                if (PyErr_Occurred())
                    goto err;
                goto fallback;
            }
            PyObject *node = PyDict_GetItemWithError(nodes, host);
            if (node == NULL) {
                Py_XDECREF(p_sc);
                if (PyErr_Occurred())
                    goto err;
                goto fallback;
            }
            PyObject *resreq = TASK_SLOT(stored, t_resreq_off);
            if (resreq == NULL || Py_TYPE(resreq) != res_type
                    || !PyFloat_Check(RES_OBJ(resreq, res_cpu_offset))
                    || !PyFloat_Check(RES_OBJ(resreq, res_mem_offset))) {
                Py_XDECREF(p_sc);
                goto fallback;
            }
            /* pending_request.sub() assert pre-check accumulation */
            PyObject *old = TASK_SLOT(stored, status_offset);
            if (old == ts_pending) {
                p_any = 1;
                p_cpu += RES_DBL(resreq, res_cpu_offset);
                p_mem += RES_DBL(resreq, res_mem_offset);
                int rc = acc_scalars(&p_sc,
                                     RES_OBJ(resreq, res_scalars_offset));
                if (rc == -1) {
                    Py_XDECREF(p_sc);
                    goto err;
                }
                if (rc == -2) {
                    Py_XDECREF(p_sc);
                    goto fallback;
                }
            }
            /* stash (burst, ti, stored) under the node, and swap the
             * by_job item for the resolved 4-tuple the mutation pass
             * reads (index 3 = stored) */
            PyObject *nlst = PyDict_GetItemWithError(by_node, host);
            if (nlst == NULL) {
                if (PyErr_Occurred()) {
                    Py_XDECREF(p_sc);
                    goto err;
                }
                nlst = PyList_New(0);
                if (nlst == NULL
                        || PyDict_SetItem(by_node, host, nlst) < 0) {
                    Py_XDECREF(nlst);
                    Py_XDECREF(p_sc);
                    goto err;
                }
                Py_DECREF(nlst);
            }
            PyObject *nitem = PyTuple_Pack(
                3, PyTuple_GET_ITEM(item, 0), ti, stored);
            if (nitem == NULL || PyList_Append(nlst, nitem) < 0) {
                Py_XDECREF(nitem);
                Py_XDECREF(p_sc);
                goto err;
            }
            Py_DECREF(nitem);
            PyObject *ritem = PyTuple_Pack(
                4, PyTuple_GET_ITEM(item, 0), ti,
                PyTuple_GET_ITEM(item, 2), stored);
            if (ritem == NULL
                    || PyList_SetItem(items, i, ritem) < 0) {  /* steals */
                Py_XDECREF(ritem);
                Py_XDECREF(p_sc);
                goto err;
            }
        }
        if (p_any) {
            int ok = le_eps_resource(p_cpu, p_mem, p_sc, pend, eps);
            Py_XDECREF(p_sc);
            if (ok < 0)
                goto err;
            if (!ok)
                goto fallback;   /* sub() would assert */
        } else
            Py_XDECREF(p_sc);
    }

    /* ---- validation: per-node accounting preconditions ---- */
    Py_ssize_t npos = 0;
    PyObject *host, *nitems;
    while (PyDict_Next(by_node, &npos, &host, &nitems)) {
        PyObject *node = PyDict_GetItem(nodes, host);   /* resolved above */
        PyObject **ndp = node == NULL ? NULL : _PyObject_GetDictPtr(node);
        if (ndp == NULL || *ndp == NULL)
            goto fallback;
        PyObject *nd = *ndp;
        PyObject *gpus = PyDict_GetItemWithError(nd, s_gpu_devices);
        if (PyErr_Occurred())
            goto err;
        int truthy = gpus == NULL ? 0 : PyObject_IsTrue(gpus);
        if (truthy != 0)
            goto fallback;   /* GPU-sharing nodes keep the Python path */
        PyObject *nname = PyDict_GetItemWithError(nd, s_name);
        PyObject *ntasks = PyDict_GetItemWithError(nd, s_tasks);
        PyObject *nodeobj = PyDict_GetItemWithError(nd, s_node);
        PyObject *idle = PyDict_GetItemWithError(nd, s_idle);
        PyObject *used = PyDict_GetItemWithError(nd, s_used);
        if (PyErr_Occurred())
            goto err;
        if (nname == NULL || ntasks == NULL || !PyDict_Check(ntasks)
                || idle == NULL || Py_TYPE(idle) != res_type
                || used == NULL || Py_TYPE(used) != res_type)
            goto fallback;
        double t_cpu = 0.0, t_mem = 0.0;
        PyObject *t_sc = NULL;
        Py_ssize_t ni = PyList_GET_SIZE(nitems);
        int bad = 0;
        PyObject *seen = PySet_New(NULL);
        if (seen == NULL)
            goto err;
        for (Py_ssize_t i = 0; i < ni && !bad; i++) {
            PyObject *stored = PyTuple_GET_ITEM(
                PyList_GET_ITEM(nitems, i), 2);
            PyObject *tn = TASK_SLOT(stored, t_node_name_off);
            int tn_t = tn == NULL ? 0 : PyObject_IsTrue(tn);
            int nn_t = PyObject_IsTrue(nname);
            if (tn_t < 0 || nn_t < 0) {
                Py_DECREF(seen);
                Py_XDECREF(t_sc);
                goto err;
            }
            if (tn_t && nn_t && !str_eq(tn, nname)) {
                bad = 1;   /* already on a different node */
                break;
            }
            PyObject *key = TASK_SLOT(stored, t_key_off);
            if (key == NULL) {
                Py_DECREF(seen);
                Py_XDECREF(t_sc);
                PyErr_SetString(PyExc_TypeError, "task lacks key_cache");
                goto err;
            }
            Py_INCREF(key);
            int dup = PyDict_Contains(ntasks, key);
            int dup2 = dup == 0 ? PySet_Contains(seen, key) : dup;
            if (dup < 0 || dup2 < 0 || PySet_Add(seen, key) < 0) {
                Py_DECREF(key);
                Py_DECREF(seen);
                Py_XDECREF(t_sc);
                goto err;
            }
            Py_DECREF(key);
            if (dup || dup2) {
                bad = 1;
                break;
            }
            PyObject *resreq = TASK_SLOT(stored, t_resreq_off);
            t_cpu += RES_DBL(resreq, res_cpu_offset);
            t_mem += RES_DBL(resreq, res_mem_offset);
            int rc = acc_scalars(&t_sc,
                                 RES_OBJ(resreq, res_scalars_offset));
            if (rc == -1) {
                Py_DECREF(seen);
                Py_XDECREF(t_sc);
                goto err;
            }
            if (rc == -2)
                bad = 1;
        }
        Py_DECREF(seen);
        if (!bad && nodeobj != NULL && nodeobj != Py_None) {
            int fit = le_eps_resource(t_cpu, t_mem, t_sc, idle, eps);
            if (fit < 0) {
                Py_XDECREF(t_sc);
                goto err;
            }
            if (!fit)
                bad = 1;
        }
        Py_XDECREF(t_sc);
        if (bad)
            goto fallback;
    }

    /* ---- mutation: per-job status moves + flips ---- */
    jpos = 0;
    while (PyDict_Next(by_job, &jpos, &jid, &items)) {
        if (PySet_Add(dirty_jobs, jid) < 0)
            goto err;
        PyObject *job = PyDict_GetItem(jobs, jid);
        PyObject *jd = *_PyObject_GetDictPtr(job);
        PyObject *jtasks = PyDict_GetItem(jd, s_tasks);
        PyObject *alloc = PyDict_GetItem(jd, s_allocated);
        PyObject *pend = PyDict_GetItem(jd, s_pending_request);
        PyObject *tsi = PyDict_GetItem(jd, s_task_status_index);  /* validated */
        if (bump_status_version(jd) < 0)
            goto err;
        /* new-status bucket up front, like move_tasks_status_bulk */
        PyObject *new_idx = PyDict_GetItemWithError(tsi, binding);
        if (new_idx == NULL) {
            if (PyErr_Occurred())
                goto err;
            new_idx = PyDict_New();
            if (new_idx == NULL
                    || PyDict_SetItem(tsi, binding, new_idx) < 0) {
                Py_XDECREF(new_idx);
                goto err;
            }
            Py_DECREF(new_idx);
            new_idx = PyDict_GetItem(tsi, binding);
        }
        double f_cpu = 0.0, f_mem = 0.0, p_cpu = 0.0, p_mem = 0.0;
        PyObject *f_sc = NULL, *p_sc = NULL;
        int f_any = 0, p_any = 0;
        Py_ssize_t ni = PyList_GET_SIZE(items);
        for (Py_ssize_t i = 0; i < ni; i++) {
            PyObject *stored = PyTuple_GET_ITEM(
                PyList_GET_ITEM(items, i), 3);   /* resolved 4-tuple */
            PyObject *uid = TASK_SLOT(stored, uid_offset);
            PyObject *old = TASK_SLOT(stored, status_offset);
            PyObject *old_idx = PyDict_GetItemWithError(tsi, old);
            if (old_idx != NULL && PyDict_Check(old_idx)) {
                if (PyDict_DelItem(old_idx, uid) < 0)
                    PyErr_Clear();
                if (PyDict_GET_SIZE(old_idx) == 0 && old != binding
                        && PyDict_DelItem(tsi, old) < 0)
                    PyErr_Clear();
            } else if (PyErr_Occurred())
                goto err;
            PyObject *resreq = TASK_SLOT(stored, t_resreq_off);
            if (PySet_Contains(ts_allocated_set, old) != 1) {
                f_any = 1;
                f_cpu += RES_DBL(resreq, res_cpu_offset);
                f_mem += RES_DBL(resreq, res_mem_offset);
                if (acc_scalars_strict(
                        &f_sc, RES_OBJ(resreq, res_scalars_offset)) < 0)
                    goto err;
            }
            if (old == ts_pending) {
                p_any = 1;
                p_cpu += RES_DBL(resreq, res_cpu_offset);
                p_mem += RES_DBL(resreq, res_mem_offset);
                if (acc_scalars_strict(
                        &p_sc, RES_OBJ(resreq, res_scalars_offset)) < 0)
                    goto err;
            }
            slot_store(stored, status_offset, binding);
            if (PyDict_SetItem(jtasks, uid, stored) < 0
                    || PyDict_SetItem(new_idx, uid, stored) < 0)
                goto err;
        }
        int rc = 0;
        if (f_any)
            rc |= apply_res_delta(alloc, f_cpu, f_mem, f_sc, +1, 1);
        if (p_any && rc == 0)
            rc |= apply_res_delta(pend, p_cpu, p_mem, p_sc, -1, 1);
        Py_XDECREF(f_sc);
        Py_XDECREF(p_sc);
        if (rc != 0)
            goto err;
    }

    /* ---- mutation: one accounting pass per node + burst results ---- */
    npos = 0;
    while (PyDict_Next(by_node, &npos, &host, &nitems)) {
        if (PySet_Add(dirty_nodes, host) < 0)
            goto err;
        PyObject *node = PyDict_GetItem(nodes, host);
        PyObject *nd = *_PyObject_GetDictPtr(node);
        PyObject *nname = PyDict_GetItem(nd, s_name);
        PyObject *ntasks = PyDict_GetItem(nd, s_tasks);
        PyObject *nodeobj = PyDict_GetItem(nd, s_node);
        PyObject *idle = PyDict_GetItem(nd, s_idle);
        PyObject *used = PyDict_GetItem(nd, s_used);
        Py_ssize_t ni = PyList_GET_SIZE(nitems);
        if (nodeobj != NULL && nodeobj != Py_None) {
            double t_cpu = 0.0, t_mem = 0.0;
            PyObject *t_sc = NULL;
            for (Py_ssize_t i = 0; i < ni; i++) {
                PyObject *resreq = TASK_SLOT(PyTuple_GET_ITEM(
                    PyList_GET_ITEM(nitems, i), 2), t_resreq_off);
                t_cpu += RES_DBL(resreq, res_cpu_offset);
                t_mem += RES_DBL(resreq, res_mem_offset);
                if (acc_scalars_strict(
                        &t_sc, RES_OBJ(resreq, res_scalars_offset)) < 0)
                    goto err;
            }
            int rc = apply_res_delta(idle, t_cpu, t_mem, t_sc, -1, 0);
            if (rc == 0)
                rc = apply_res_delta(used, t_cpu, t_mem, t_sc, +1, 1);
            Py_XDECREF(t_sc);
            if (rc != 0)
                goto err;
        }
        PyObject *last_burst = NULL, *accepted = NULL, *bound = NULL;
        for (Py_ssize_t i = 0; i < ni; i++) {
            PyObject *nitem = PyList_GET_ITEM(nitems, i);
            PyObject *burst = PyTuple_GET_ITEM(nitem, 0);
            PyObject *ti = PyTuple_GET_ITEM(nitem, 1);
            PyObject *stored = PyTuple_GET_ITEM(nitem, 2);
            PyObject *key = TASK_SLOT(stored, t_key_off);
            if (key == NULL) {
                PyErr_SetString(PyExc_TypeError, "task lacks key_cache");
                goto err;
            }
            Py_INCREF(key);
            PyObject *clone = clone_one(stored);
            if (clone == NULL) {
                Py_DECREF(key);
                goto err;
            }
            slot_store(stored, t_node_name_off, nname);
            slot_store(clone, t_node_name_off, nname);
            int rc = PyDict_SetItem(ntasks, key, clone);
            Py_DECREF(clone);
            Py_DECREF(key);
            if (rc < 0)
                goto err;
            if (burst != last_burst) {
                Py_XDECREF(accepted);
                Py_XDECREF(bound);
                accepted = PyObject_GetAttr(burst, s_accepted);
                bound = PyObject_GetAttr(burst, s_bound);
                last_burst = burst;
                if (accepted == NULL || bound == NULL) {
                    Py_XDECREF(accepted);
                    Py_XDECREF(bound);
                    goto err;
                }
            }
            PyObject *bt = PyTuple_Pack(3, stored,
                                        TASK_SLOT(stored, t_pod_off), host);
            if (bt == NULL || PyList_Append(accepted, ti) < 0
                    || PyList_Append(bound, bt) < 0) {
                Py_XDECREF(bt);
                Py_XDECREF(accepted);
                Py_XDECREF(bound);
                goto err;
            }
            Py_DECREF(bt);
        }
        Py_XDECREF(accepted);
        Py_XDECREF(bound);
    }
    Py_DECREF(by_job);
    Py_DECREF(by_node);
    Py_RETURN_TRUE;

fallback:
    Py_XDECREF(by_job);
    Py_XDECREF(by_node);
    Py_RETURN_FALSE;
err:
    Py_XDECREF(by_job);
    Py_XDECREF(by_node);
    return NULL;
}

/* attr_eq_filter_pairs(pairs, attr0, attr1, expected)
 *     -> (delivery, flips)
 *
 * Watch-filter evaluation for one bulk delivery when the watcher
 * declared its filter as an attribute equality
 * (Watch.filter_attr — obj.<attr0>.<attr1> == expected): pass->pass
 * pairs collect into the delivery list; filter FLIPS come back as
 * ordered (is_add, obj) events — fail->pass yields (True, new),
 * pass->fail (False, old) — in pair order, so the caller fires
 * on_add/on_delete exactly as the per-pair Python loop would.
 * fail->fail drops. Two Python filter calls per pod otherwise. */
static PyObject *
attr_eq_filter_pairs(PyObject *self, PyObject *args)
{
    PyObject *pairs, *attr0, *attr1, *expected;
    if (!PyArg_ParseTuple(args, "O!UUO", &PyList_Type, &pairs,
                          &attr0, &attr1, &expected))
        return NULL;
    PyObject *delivery = PyList_New(0);
    PyObject *flips = PyList_New(0);
    if (delivery == NULL || flips == NULL)
        goto fail;
    Py_ssize_t n = PyList_GET_SIZE(pairs);
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *pair = PyList_GET_ITEM(pairs, i);
        if (!PyTuple_Check(pair) || PyTuple_GET_SIZE(pair) != 2) {
            PyErr_SetString(PyExc_TypeError, "pairs items must be 2-tuples");
            goto fail;
        }
        PyObject *old = PyTuple_GET_ITEM(pair, 0);
        PyObject *new = PyTuple_GET_ITEM(pair, 1);
        int flags[2];
        PyObject *objs[2] = {old, new};
        for (int k = 0; k < 2; k++) {
            PyObject **dp = _PyObject_GetDictPtr(objs[k]);
            PyObject *sub = (dp == NULL || *dp == NULL) ? NULL
                : PyDict_GetItemWithError(*dp, attr0);
            PyObject *val = sub == NULL ? NULL : dict_attr(sub, attr1);
            if (PyErr_Occurred())
                goto fail;
            if (val == NULL || (!PyUnicode_Check(val)
                                && val != Py_None)) {
                /* unexpected shape: fall back to the Python filter */
                PyErr_SetString(PyExc_TypeError, "unfilterable shape");
                goto fail;
            }
            flags[k] = str_eq(val, expected);
        }
        if (flags[0] && flags[1]) {
            if (PyList_Append(delivery, pair) < 0)
                goto fail;
        } else if (flags[0] != flags[1]) {
            PyObject *ev = PyTuple_Pack(
                2, flags[1] ? Py_True : Py_False, flags[1] ? new : old);
            if (ev == NULL || PyList_Append(flips, ev) < 0) {
                Py_XDECREF(ev);
                goto fail;
            }
            Py_DECREF(ev);
        }
    }
    return Py_BuildValue("(NN)", delivery, flips);
fail:
    Py_XDECREF(delivery);
    Py_XDECREF(flips);
    return NULL;
}

/* bind_request_items(items) -> (requests, keys)
 *
 * The binder-seam list plumbing of one flush in a single pass: items is
 * [(pod, hostname)]; returns ([(name, namespace, hostname)] — the
 * store.bind_pods request — and the parallel ["ns/name"] key list the
 * binder's bind-channel recording wants). Two interpreted listcomps +
 * 50k f-strings on the drain thread otherwise. */
static PyObject *
bind_request_items(PyObject *self, PyObject *args)
{
    PyObject *items;
    int want_reqs = 1, want_keys = 1;
    if (!PyArg_ParseTuple(args, "O!|pp", &PyList_Type, &items,
                          &want_reqs, &want_keys))
        return NULL;
    Py_ssize_t n = PyList_GET_SIZE(items);
    PyObject *reqs = want_reqs ? PyList_New(n) : (Py_INCREF(Py_None),
                                                  Py_None);
    PyObject *keys = want_keys ? PyList_New(n) : (Py_INCREF(Py_None),
                                                  Py_None);
    if (reqs == NULL || keys == NULL)
        goto fail;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *it = PyList_GET_ITEM(items, i);
        if (!PyTuple_Check(it) || PyTuple_GET_SIZE(it) != 2) {
            PyErr_SetString(PyExc_TypeError, "items must be (pod, host)");
            goto fail;
        }
        PyObject *pod = PyTuple_GET_ITEM(it, 0);
        PyObject *host = PyTuple_GET_ITEM(it, 1);
        PyObject **pdp = _PyObject_GetDictPtr(pod);
        PyObject *meta = (pdp == NULL || *pdp == NULL) ? NULL
            : PyDict_GetItemWithError(*pdp, s_metadata);
        PyObject *name = meta == NULL ? NULL : dict_attr(meta, s_name);
        PyObject *ns = meta == NULL ? NULL
            : dict_attr(meta, s_namespace_str);
        if (name == NULL || ns == NULL) {
            if (!PyErr_Occurred())
                PyErr_SetString(PyExc_TypeError,
                                "pod lacks metadata name/namespace");
            goto fail;
        }
        if (want_reqs) {
            PyObject *req = PyTuple_New(3);
            if (req == NULL)
                goto fail;
            Py_INCREF(name);
            PyTuple_SET_ITEM(req, 0, name);
            Py_INCREF(ns);
            PyTuple_SET_ITEM(req, 1, ns);
            Py_INCREF(host);
            PyTuple_SET_ITEM(req, 2, host);
            PyList_SET_ITEM(reqs, i, req);
        }
        if (want_keys) {
            PyObject *key = PyUnicode_FromFormat("%U/%U", ns, name);
            if (key == NULL)
                goto fail;
            PyList_SET_ITEM(keys, i, key);
        }
    }
    return Py_BuildValue("(NN)", reqs, keys);
fail:
    Py_XDECREF(reqs);
    Py_XDECREF(keys);
    return NULL;
}

static PyObject *
shell_clone(PyObject *self, PyObject *src)
{
    PyTypeObject *tp = Py_TYPE(src);
    PyObject *d = PyObject_GetAttrString(src, "__dict__");
    if (d == NULL)
        return NULL;
    PyObject *nd = PyDict_Copy(d);
    Py_DECREF(d);
    if (nd == NULL)
        return NULL;
    PyObject *dst = tp->tp_alloc(tp, 0);
    if (dst == NULL) {
        Py_DECREF(nd);
        return NULL;
    }
    if (PyObject_SetAttrString(dst, "__dict__", nd) < 0) {
        Py_DECREF(nd);
        Py_DECREF(dst);
        return NULL;
    }
    Py_DECREF(nd);
    return dst;
}

/* ---- shared-bytes frame encoder (hub.encoder fast path) ------------
 *
 * encode_object_json(o) -> bytes: the C twin of http.py's
 * json_object_encoder — codec.encode() (dataclass reflection walk)
 * fused with json.dumps(separators=(",", ":")) into one pass over the
 * object graph, emitting straight into a growing byte buffer.  The
 * contract is BYTE parity: the hub splices these bytes verbatim into
 * every subscriber's NDJSON frame and the replication fingerprints crc
 * them, so a single divergent float repr or escape choice is a
 * cross-replica audit failure.  Parity choices, each pinned by
 * tests/test_native_encoder.py:
 *   - dataclass fields in dataclasses.fields() order (resolved once
 *     per type through the real dataclasses.fields, cached);
 *   - dict keys str()-ed like codec.encode, insertion order kept;
 *   - bytes -> {"__bytes__": "<base64>"} exactly as codec.encode;
 *   - ensure_ascii \uXXXX escapes (surrogate pairs for astral),
 *     int/float via int.__repr__/float.__repr__ like the stdlib
 *     C encoder (so bool-masquerading ints and shortest-repr floats
 *     cannot drift), NaN/Infinity spelled as json.dumps spells them.
 * Any shape this walker does not recognize raises, and the guarded
 * call site falls back to the Python body for that object. */

static PyObject *dc_fields_func = NULL;   /* dataclasses.fields */
static PyObject *dc_field_cache = NULL;   /* type -> (name str, ...) */
static PyObject *s_dataclass_fields, *s_field_name;

typedef struct {
    char *buf;
    Py_ssize_t len, cap;
} jbuf;

static int
jbuf_grow(jbuf *b, Py_ssize_t extra)
{
    if (b->len + extra <= b->cap)
        return 0;
    Py_ssize_t cap = b->cap ? b->cap : 256;
    while (cap < b->len + extra)
        cap *= 2;
    char *nb = PyMem_Realloc(b->buf, cap);
    if (nb == NULL) {
        PyErr_NoMemory();
        return -1;
    }
    b->buf = nb;
    b->cap = cap;
    return 0;
}

static int
jbuf_put(jbuf *b, const char *s, Py_ssize_t n)
{
    if (jbuf_grow(b, n) < 0)
        return -1;
    memcpy(b->buf + b->len, s, n);
    b->len += n;
    return 0;
}

static int
jbuf_putc(jbuf *b, char c)
{
    return jbuf_put(b, &c, 1);
}

/* the str()/repr() of o as ASCII bytes into the buffer (int/float
 * reprs are always ASCII) */
static int
jbuf_put_ascii_repr(jbuf *b, PyObject *r)
{
    Py_ssize_t n;
    const char *s = PyUnicode_AsUTF8AndSize(r, &n);
    if (s == NULL)
        return -1;
    return jbuf_put(b, s, n);
}

/* json.dumps ensure_ascii string escape: ", \\, \b \f \n \r \t,
 * \u00XX for other control chars, \uXXXX for everything >= 0x7f
 * (surrogate pairs above the BMP) */
static int
jbuf_put_escaped(jbuf *b, PyObject *str)
{
    if (PyUnicode_READY(str) < 0)
        return -1;
    Py_ssize_t n = PyUnicode_GET_LENGTH(str);
    int kind = PyUnicode_KIND(str);
    const void *data = PyUnicode_DATA(str);
    static const char *hex = "0123456789abcdef";
    if (jbuf_putc(b, '"') < 0)
        return -1;
    for (Py_ssize_t i = 0; i < n; i++) {
        Py_UCS4 c = PyUnicode_READ(kind, data, i);
        if (c >= 0x20 && c < 0x7f && c != '"' && c != '\\') {
            if (jbuf_putc(b, (char)c) < 0)
                return -1;
            continue;
        }
        char esc[12];
        Py_ssize_t m;
        switch (c) {
        case '"':  esc[0] = '\\'; esc[1] = '"';  m = 2; break;
        case '\\': esc[0] = '\\'; esc[1] = '\\'; m = 2; break;
        case '\b': esc[0] = '\\'; esc[1] = 'b';  m = 2; break;
        case '\f': esc[0] = '\\'; esc[1] = 'f';  m = 2; break;
        case '\n': esc[0] = '\\'; esc[1] = 'n';  m = 2; break;
        case '\r': esc[0] = '\\'; esc[1] = 'r';  m = 2; break;
        case '\t': esc[0] = '\\'; esc[1] = 't';  m = 2; break;
        default:
            if (c >= 0x10000) {
                /* astral plane: UTF-16 surrogate pair, like the
                 * stdlib's ensure_ascii encoder */
                Py_UCS4 v = c - 0x10000;
                Py_UCS4 hi = 0xd800 + (v >> 10);
                Py_UCS4 lo = 0xdc00 + (v & 0x3ff);
                esc[0] = '\\'; esc[1] = 'u';
                esc[2] = hex[(hi >> 12) & 0xf];
                esc[3] = hex[(hi >> 8) & 0xf];
                esc[4] = hex[(hi >> 4) & 0xf];
                esc[5] = hex[hi & 0xf];
                esc[6] = '\\'; esc[7] = 'u';
                esc[8] = hex[(lo >> 12) & 0xf];
                esc[9] = hex[(lo >> 8) & 0xf];
                esc[10] = hex[(lo >> 4) & 0xf];
                esc[11] = hex[lo & 0xf];
                m = 12;
            } else {
                esc[0] = '\\'; esc[1] = 'u';
                esc[2] = hex[(c >> 12) & 0xf];
                esc[3] = hex[(c >> 8) & 0xf];
                esc[4] = hex[(c >> 4) & 0xf];
                esc[5] = hex[c & 0xf];
                m = 6;
            }
        }
        if (jbuf_put(b, esc, m) < 0)
            return -1;
    }
    return jbuf_putc(b, '"');
}

/* bytes -> {"__bytes__":"<standard base64, padded>"} — the codec's
 * base64.b64encode rendering */
static int
jbuf_put_bytes(jbuf *b, PyObject *bytes)
{
    static const char *b64 =
        "ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz"
        "0123456789+/";
    const unsigned char *p = (const unsigned char *)PyBytes_AS_STRING(bytes);
    Py_ssize_t n = PyBytes_GET_SIZE(bytes);
    if (jbuf_put(b, "{\"__bytes__\":\"", 14) < 0)
        return -1;
    for (Py_ssize_t i = 0; i < n; i += 3) {
        unsigned v = p[i] << 16;
        if (i + 1 < n) v |= p[i + 1] << 8;
        if (i + 2 < n) v |= p[i + 2];
        char q[4];
        q[0] = b64[(v >> 18) & 63];
        q[1] = b64[(v >> 12) & 63];
        q[2] = i + 1 < n ? b64[(v >> 6) & 63] : '=';
        q[3] = i + 2 < n ? b64[v & 63] : '=';
        if (jbuf_put(b, q, 4) < 0)
            return -1;
    }
    return jbuf_put(b, "\"}", 2);
}

/* the type's dataclass field-name tuple (dataclasses.fields order —
 * NOT __dataclass_fields__, which also carries ClassVar/InitVar
 * pseudo-fields), cached per type; NULL = not a dataclass instance
 * (no exception) or error (exception set) */
static PyObject *
dc_field_names(PyObject *o)
{
    PyObject *tp = (PyObject *)Py_TYPE(o);
    int has = PyObject_HasAttr(tp, s_dataclass_fields);
    if (!has)
        return NULL;
    PyObject *cached = PyDict_GetItemWithError(dc_field_cache, tp);
    if (cached != NULL || PyErr_Occurred())
        return cached;
    if (dc_fields_func == NULL) {
        PyObject *mod = PyImport_ImportModule("dataclasses");
        if (mod == NULL)
            return NULL;
        dc_fields_func = PyObject_GetAttrString(mod, "fields");
        Py_DECREF(mod);
        if (dc_fields_func == NULL)
            return NULL;
    }
    PyObject *fields = PyObject_CallFunctionObjArgs(dc_fields_func, o,
                                                    NULL);
    if (fields == NULL)
        return NULL;
    Py_ssize_t n = PySequence_Length(fields);
    PyObject *names = n < 0 ? NULL : PyTuple_New(n);
    if (names == NULL) {
        Py_DECREF(fields);
        return NULL;
    }
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *f = PySequence_GetItem(fields, i);
        PyObject *name = f == NULL ? NULL
            : PyObject_GetAttr(f, s_field_name);
        Py_XDECREF(f);
        if (name == NULL || !PyUnicode_Check(name)) {
            Py_XDECREF(name);
            Py_DECREF(names);
            Py_DECREF(fields);
            if (!PyErr_Occurred())
                PyErr_SetString(PyExc_TypeError,
                                "dataclass field name is not a str");
            return NULL;
        }
        PyTuple_SET_ITEM(names, i, name);
    }
    Py_DECREF(fields);
    if (PyDict_SetItem(dc_field_cache, tp, names) < 0) {
        Py_DECREF(names);
        return NULL;
    }
    Py_DECREF(names);           /* cache holds the reference */
    return PyDict_GetItemWithError(dc_field_cache, tp);
}

static int jenc(jbuf *b, PyObject *o);

static int
jenc_kv(jbuf *b, PyObject *key, PyObject *val, int first)
{
    if (!first && jbuf_putc(b, ',') < 0)
        return -1;
    if (jbuf_put_escaped(b, key) < 0)
        return -1;
    if (jbuf_putc(b, ':') < 0)
        return -1;
    return jenc(b, val);
}

static int
jenc(jbuf *b, PyObject *o)
{
    if (o == Py_None)
        return jbuf_put(b, "null", 4);
    if (o == Py_True)
        return jbuf_put(b, "true", 4);
    if (o == Py_False)
        return jbuf_put(b, "false", 5);
    if (PyLong_Check(o)) {
        /* int.__repr__, not repr(o): an int SUBCLASS must serialize
         * as its integer value, exactly like the stdlib encoder */
        PyObject *r = PyLong_Type.tp_repr(o);
        if (r == NULL)
            return -1;
        int rc = jbuf_put_ascii_repr(b, r);
        Py_DECREF(r);
        return rc;
    }
    if (PyFloat_Check(o)) {
        double d = PyFloat_AS_DOUBLE(o);
        if (isnan(d))
            return jbuf_put(b, "NaN", 3);
        if (isinf(d))
            return d > 0 ? jbuf_put(b, "Infinity", 8)
                         : jbuf_put(b, "-Infinity", 9);
        PyObject *r = PyFloat_Type.tp_repr(o);   /* shortest repr */
        if (r == NULL)
            return -1;
        int rc = jbuf_put_ascii_repr(b, r);
        Py_DECREF(r);
        return rc;
    }
    if (PyUnicode_Check(o))
        return jbuf_put_escaped(b, o);
    if (PyBytes_Check(o))
        return jbuf_put_bytes(b, o);
    if (PyDict_Check(o)) {
        if (jbuf_putc(b, '{') < 0)
            return -1;
        PyObject *key, *val;
        Py_ssize_t pos = 0;
        int first = 1;
        while (PyDict_Next(o, &pos, &key, &val)) {
            /* codec.encode str()s every key before json sees it */
            PyObject *ks = PyUnicode_Check(key)
                ? (Py_INCREF(key), key) : PyObject_Str(key);
            if (ks == NULL)
                return -1;
            int rc = jenc_kv(b, ks, val, first);
            Py_DECREF(ks);
            if (rc < 0)
                return -1;
            first = 0;
        }
        return jbuf_putc(b, '}');
    }
    if (PyList_Check(o) || PyTuple_Check(o)) {
        if (jbuf_putc(b, '[') < 0)
            return -1;
        Py_ssize_t n = PySequence_Fast_GET_SIZE(o);
        PyObject **items = PySequence_Fast_ITEMS(o);
        for (Py_ssize_t i = 0; i < n; i++) {
            if (i && jbuf_putc(b, ',') < 0)
                return -1;
            if (jenc(b, items[i]) < 0)
                return -1;
        }
        return jbuf_putc(b, ']');
    }
    if (!PyType_Check(o)) {
        PyObject *names = dc_field_names(o);
        if (names != NULL) {
            if (jbuf_putc(b, '{') < 0)
                return -1;
            Py_ssize_t n = PyTuple_GET_SIZE(names);
            for (Py_ssize_t i = 0; i < n; i++) {
                PyObject *name = PyTuple_GET_ITEM(names, i);
                PyObject *val = PyObject_GetAttr(o, name);
                if (val == NULL)
                    return -1;
                int rc = jenc_kv(b, name, val, i == 0);
                Py_DECREF(val);
                if (rc < 0)
                    return -1;
            }
            return jbuf_putc(b, '}');
        }
        if (PyErr_Occurred())
            return -1;
    }
    PyErr_Format(PyExc_TypeError,
                 "encode_object_json: unencodable type %.100s "
                 "(caller falls back to the Python codec)",
                 Py_TYPE(o)->tp_name);
    return -1;
}

static PyObject *
encode_object_json(PyObject *self, PyObject *o)
{
    jbuf b = {NULL, 0, 0};
    if (jenc(&b, o) < 0) {
        PyMem_Free(b.buf);
        return NULL;
    }
    PyObject *out = PyBytes_FromStringAndSize(b.buf, b.len);
    PyMem_Free(b.buf);
    return out;
}

static PyMethodDef methods[] = {
    {"register_task_type", register_task_type, METH_O,
     "Register the TaskInfo class (reads slot offsets)."},
    /* lint: allow(native-fallback-parity, clone_task): test seam — the
     * per-slot clone primitive clone_task_table/clone_task_dict build
     * on; exercised directly by tests/test_native_model.py, no package
     * call site by design (the table/dict entries are the fallbacked
     * production paths). */
    {"clone_task", clone_task, METH_O, "Verbatim slot-copy clone."},
    {"clone_task_table", clone_task_table, METH_O,
     "Clone a job's task dict and build the status index."},
    {"clone_task_dict", clone_task_dict, METH_O,
     "Clone a node's task dict (no index)."},
    {"register_resource_type", register_resource_type, METH_O,
     "Register the Resource class (reads slot offsets)."},
    {"clone_resource", clone_resource, METH_O,
     "Slot-copy Resource clone with a fresh scalars dict."},
    {"shell_clone", shell_clone, METH_O,
     "New instance of type(obj) with a shallow __dict__ copy."},
    {"bind_clone_pods", bind_clone_pods, METH_VARARGS,
     "Batch bind clone: minimal pod shells with node_name + rv set."},
    {"register_task_status", register_task_status, METH_VARARGS,
     "Register TaskStatus members + the allocated-status set."},
    {"register_ledger_types", register_ledger_types, METH_VARARGS,
     "Register the ledger _Entry/_Agg types + hop-name table."},
    {"ledger_confirm_runs", ledger_confirm_runs, METH_VARARGS,
     "Bind-echo ledger completion for a whole delivery's runs."},
    {"publish_shard", publish_shard, METH_VARARGS,
     "Install one bulk-patch shard: objects, barrier release, journal "
     "entries and delivery pairs in one pass."},
    {"bind_echo_apply", bind_echo_apply, METH_VARARGS,
     "Expected-bind-echo ingest of one bulk delivery: guards, status "
     "index moves, rv refresh, node-view sync, ledger run grouping."},
    {"attr_eq_filter_pairs", attr_eq_filter_pairs, METH_VARARGS,
     "Bulk watch-filter classification for attribute-equality filters."},
    {"bind_request_items", bind_request_items, METH_VARARGS,
     "Binder-seam plumbing: [(pod, host)] -> ([(name, ns, host)], "
     "[\"ns/name\"])."},
    {"bind_apply_bursts", bind_apply_bursts, METH_VARARGS,
     "Coalesced cross-gang bind apply: per-job status moves + one "
     "accounting pass per node, all-or-nothing with Python fallback."},
    {"encode_object_json", encode_object_json, METH_O,
     "Shared-bytes frame encode: codec.encode + compact json.dumps "
     "fused into one pass, byte-identical to the Python pair."},
    {NULL, NULL, 0, NULL}
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "fastmodel",
    "C accelerators for snapshot cloning.", -1, methods
};

PyMODINIT_FUNC
PyInit_fastmodel(void)
{
    s_metadata = PyUnicode_InternFromString("metadata");
    s_spec = PyUnicode_InternFromString("spec");
    s_node_name = PyUnicode_InternFromString("node_name");
    s_resource_version = PyUnicode_InternFromString("resource_version");
    s_modified = PyUnicode_InternFromString("MODIFIED");
    s_uid = PyUnicode_InternFromString("uid");
    s_deletion_timestamp = PyUnicode_InternFromString("deletion_timestamp");
    s_phase = PyUnicode_InternFromString("phase");
    s_status = PyUnicode_InternFromString("status");
    s_task_status_index = PyUnicode_InternFromString("task_status_index");
    s_tasks = PyUnicode_InternFromString("tasks");
    s_queue = PyUnicode_InternFromString("queue");
    s_status_version = PyUnicode_InternFromString("_status_version");
    ph_running = PyUnicode_InternFromString("Running");
    ph_pending = PyUnicode_InternFromString("Pending");
    ph_succeeded = PyUnicode_InternFromString("Succeeded");
    ph_failed = PyUnicode_InternFromString("Failed");
    s_pairs = PyUnicode_InternFromString("pairs");
    s_accepted = PyUnicode_InternFromString("accepted");
    s_bound = PyUnicode_InternFromString("bound");
    s_idle = PyUnicode_InternFromString("idle");
    s_used = PyUnicode_InternFromString("used");
    s_name = PyUnicode_InternFromString("name");
    s_node = PyUnicode_InternFromString("node");
    s_gpu_devices = PyUnicode_InternFromString("gpu_devices");
    s_allocated = PyUnicode_InternFromString("allocated");
    s_pending_request = PyUnicode_InternFromString("pending_request");
    s_namespace_str = PyUnicode_InternFromString("namespace");
    s_append = PyUnicode_InternFromString("append");
    s_hop = PyUnicode_InternFromString("hop");
    s_queue_label = PyUnicode_InternFromString("queue");
    s_dataclass_fields = PyUnicode_InternFromString("__dataclass_fields__");
    s_field_name = PyUnicode_InternFromString("name");
    dc_field_cache = PyDict_New();
    if (s_metadata == NULL || s_spec == NULL || s_node_name == NULL ||
        s_resource_version == NULL || s_modified == NULL || s_uid == NULL ||
        s_deletion_timestamp == NULL || s_phase == NULL || s_status == NULL ||
        s_task_status_index == NULL || s_tasks == NULL || s_queue == NULL ||
        s_status_version == NULL || ph_running == NULL ||
        ph_pending == NULL || ph_succeeded == NULL || ph_failed == NULL ||
        s_pairs == NULL || s_accepted == NULL || s_bound == NULL ||
        s_idle == NULL || s_used == NULL || s_name == NULL ||
        s_node == NULL || s_gpu_devices == NULL || s_allocated == NULL ||
        s_pending_request == NULL || s_namespace_str == NULL ||
        s_append == NULL || s_hop == NULL || s_queue_label == NULL ||
        s_dataclass_fields == NULL || s_field_name == NULL ||
        dc_field_cache == NULL)
        return NULL;
    return PyModule_Create(&moduledef);
}
