// Native CPU gang-allocate solver.
//
// The production TPU path is the Pallas kernel (ops/pallas_allocate.py);
// off-TPU the framework previously ran the chunked XLA scan
// (ops/allocate.py gang_allocate_chunked).  XLA-on-CPU pays per-step scan
// overhead plus a full [N,R] checkpoint copy at every gang boundary; this
// native kernel implements the same decision procedure directly:
//
//   * a top-C2-per-fit-class candidate table (C2 >= the XLA kernel's
//     chunk) refreshed on group-CONTENT change (rows memcmp-verified),
//     bucket-chain change, or budget exhaustion — shape-identical gang
//     bursts (the production conf) sweep nodes ~T/C2 times total;
//   * per-constraint-slot SUB-tables (Args.S > 0): the constraint
//     compiler's per-task topology domains (task_slot [T] / slot_ok
//     [S+1,N], ops/constraints.py) serve from a top-C2 table restricted
//     to their domain, all (1+S) tables rebuilt in the ONE pass-A sweep
//     of a refresh and kept complete by apply-time overflow insertion —
//     a gang whose tasks rotate domains amortizes refreshes exactly
//     like an unconstrained one (the Solver::rows comment carries the
//     per-table dominance argument);
//   * a branchless two-pass node sweep over plane-transposed state
//     (auto-vectorizes; the XLA kernel materializes the same sweep per
//     refresh inside lax.scan);
//   * gang rollback via an undo log holding pre-placement values (the XLA
//     kernel restores a full [N,R] checkpoint copy per boundary);
//   * per-row cached serve scores, recomputed only on touch / sb change.
//
// EXACTNESS: decisions (assign / pipelined / ready / kept) match
// ops/allocate.gang_allocate (the plain scan, the semantic ground truth)
// bit-for-bit on every fuzz shape, up to sub-ulp score TIES at scale:
// XLA's fused emission is context-dependent, so two nodes whose scores
// are bit-identical under one compiled program can differ by 1 ulp under
// another — on exact ties the argmax choice may legitimately differ
// (both placements carry equal scores; gang outcomes and counts still
// match — the same cross-backend contract the Pallas kernel carries,
// tests/test_pallas_allocate.py).  The dominance argument mirrors
// ops/sharded.py's chunked
// kernel: within a table's lifetime at most C2-1 nodes are touched, only
// placed-on nodes change score/feasibility, every placed-on node is in the
// table, and an untouched node outside the table is dominated (score desc,
// index asc — lax.top_k's tie order) by at least one untouched in-table
// entry of its own class.  Table reuse across jobs additionally requires
// the (req row, mask row, static row, pack bonus) CONTENT to be equal,
// which is verified by memcmp, and a bucket change forces a refresh
// exactly like the XLA kernel's `b != prev_b` condition.  Float32
// arithmetic follows ops/score.py's operation order; the build compiles
// with -ffp-contract=off and the score formula's one contracted mul+add
// chain is written as explicit std::fmaf (node_score_base / row_score),
// matching XLA:CPU's FMA emission site-for-site (see native/build.py —
// with no fusing at all, near-tie scores differed by 1-2 ulp and flipped
// argmax tie-breaks; blanket contraction over-fused other sites).
// Parity is pinned by tests/test_native_kernel.py fuzz vs the scan,
// including adversarial near-tie stress shapes.
//
// Reference semantics: pkg/scheduler/actions/allocate/allocate.go:120-270
// (namespace/queue priority queues, per-task predicate+score+argmax,
// Statement commit/discard) — see ops/allocate.py's docstring for the
// mapping.

#include <cstdint>
#include <cstring>
#include <cstdio>
#include <cstdlib>
#include <cmath>
#include <chrono>
#include <vector>
#include <algorithm>

namespace {

constexpr float NEG = -1e30f;
constexpr float BIG = 1e30f;

struct Weights {
  const float* binpack_res;  // [R]
  float binpack, least, most, balanced;
};

// node_score (ops/score.py node_score): used = alloc - idle;
// s = w_bp*binpack + w_least*least + w_most*most + w_bal*balanced.
// Operation order matches the jnp formulation exactly; the caller adds
// static_bonus (jnp adds it last: `return s + static_bonus`).
static inline float node_score_base(const float* req, const float* idle,
                                    const float* alloc, const Weights& w,
                                    int R) {
  float bp;
  {
    float acc = 0.0f;
    float wsum = 0.0f;
    for (int r = 0; r < R; ++r) {
      bool requested = (req[r] > 0.0f) && (w.binpack_res[r] > 0.0f);
      float used = alloc[r] - idle[r];
      float denom = std::max(alloc[r], 1e-9f);
      float frac = (alloc[r] > 0.0f) ? (used + req[r]) / denom : 2.0f;
      float per = (frac <= 1.0f) ? frac * 100.0f : 0.0f;
      float wr = requested ? w.binpack_res[r] : 0.0f;
      acc += per * wr;
      wsum += wr;
    }
    wsum = std::max(wsum, 1e-9f);
    bp = acc / wsum;
  }
  float fl[2], fm[2], fb[2];
  for (int r = 0; r < 2; ++r) {
    float a = alloc[r];
    float u = (a - idle[r]) + req[r];
    float denom = std::max(a, 1e-9f);
    fl[r] = (a > 0.0f) ? std::max(a - u, 0.0f) / denom : 0.0f;
    fm[r] = (a > 0.0f) ? std::min(std::max(u, 0.0f), a) / denom : 0.0f;
    fb[r] = (a > 0.0f) ? u / denom : 0.0f;
  }
  float least = (fl[0] * 100.0f + fl[1] * 100.0f) / 2.0f;
  float most = (fm[0] * 100.0f + fm[1] * 100.0f) / 2.0f;
  float balanced = 100.0f - std::fabs(fb[0] - fb[1]) * 100.0f;
  // the weighted accumulation is the ONE chain XLA:CPU contracts to FMA
  // (jnp `s = s + w * term`); explicit fmaf matches it bitwise while the
  // build keeps -ffp-contract=off everywhere else (blanket contraction
  // over-fused other sites and broke parity the other way)
  float s = w.binpack * bp;
  s = std::fmaf(w.least, least, s);
  s = std::fmaf(w.most, most, s);
  s = std::fmaf(w.balanced, balanced, s);
  return s;
}

static inline bool fits(const float* req, const float* avail,
                        const float* eps, int R) {
  for (int r = 0; r < R; ++r)
    if (!(req[r] <= avail[r] + eps[r])) return false;
  return true;
}

static inline float queue_share_one(const float* alloc, const float* dsrv,
                                    int R) {
  float m = 0.0f;
  for (int r = 0; r < R; ++r) {
    float d = dsrv[r];
    float frac;
    if (std::isinf(d)) frac = 0.0f;
    else if (d == 0.0f) frac = (alloc[r] == 0.0f) ? 0.0f : 1.0f;
    else frac = alloc[r] / d;
    m = std::max(m, frac);
  }
  return m;
}

static inline bool queue_overused_one(const float* alloc, const float* dsrv,
                                      const float* eps, int R) {
  for (int r = 0; r < R; ++r) {
    bool le = (alloc[r] <= dsrv[r] + eps[r]) || std::isinf(dsrv[r]);
    if (!le) return true;
  }
  return false;
}

static inline float ns_share_one(const float* alloc, const float* total,
                                 float weight, int R) {
  float m = 0.0f;
  for (int r = 0; r < R; ++r) {
    float frac = (total[r] > 0.0f) ? alloc[r] / total[r]
                                   : (alloc[r] == 0.0f ? 0.0f : 1.0f);
    m = std::max(m, frac);
  }
  return m / weight;
}

struct Args {
  int32_t T, G, J, Q, P, NS, N, R;
  int32_t C2;                 // candidate-table size per fit class
  int32_t S;                  // constraint slots (0 = none)
  const int32_t* task_group;
  const int32_t* task_job;
  const uint8_t* task_valid;
  const int32_t* task_slot;   // [T] slot per task (S = unconstrained)
  const float* group_req;     // [G,R]
  const uint8_t* group_mask;  // [G,N]
  const float* group_static;  // [G,N]
  const uint8_t* slot_ok;     // [S+1,N] domain rows (row S all-true)
  const int32_t* task_bucket;
  const float* pack_bonus;    // [G]
  const int32_t* job_min;     // [J]
  const int32_t* job_base;
  const int32_t* job_start;
  const int32_t* job_ntasks;
  const int32_t* pool_queue;  // [P]
  const int32_t* pool_ns;
  const int32_t* pool_job_start;
  const int32_t* pool_njobs;
  const float* ns_weight;     // [NS]
  const float* ns_alloc0;     // [NS,R]
  const float* ns_total;      // [R]
  const float* q_deserved;    // [Q,R]
  const float* q_alloc0;      // [Q,R]
  const float* node_idle;     // [N,R]
  const float* node_future;
  const float* node_alloc;
  const int32_t* node_ntasks; // [N]
  const int32_t* node_max;    // [N]
  const float* eps;           // [R]
  const float* binpack_res;   // [R]
  float w_binpack, w_least, w_most, w_balanced;
  int32_t allow_pipeline, ns_live;
  // outputs
  int32_t* assign;            // [T]
  uint8_t* out_pipelined;     // [T]
  uint8_t* out_ready;         // [J]
  uint8_t* out_kept;          // [J]
  float* out_idle;            // [N,R]
};

struct Solver {
  const Args& a;
  int N, R;
  Weights w;
  // cluster state in PLANE layout [r][n] (auto-vectorizable sweeps);
  // alloc planes are read-only copies of the input
  std::vector<float> idleT, futT, allocT;   // [R*N]
  std::vector<int32_t> ntasks;              // [N]
  // pack chain state (NOT rolled back on gang discard — scan semantics)
  std::vector<float> pack_val;              // [N]
  std::vector<int32_t> pack_epoch;          // [N]
  int32_t epoch = 1;
  int32_t cur_bucket = -1;
  // bookkeeping
  std::vector<float> q_alloc, ns_alloc;     // [Q,R] / [NS,R]
  std::vector<int32_t> p_cursor;            // [P]
  std::vector<uint8_t> ready, kept;         // [J]

  // sweep buffers (pass A writes, pass B reads)
  std::vector<float> sw_rank, sw_serve;     // [N]
  std::vector<uint8_t> sw_fi, sw_ff;        // [N]

  // Candidate tables. Table 0 is the GLOBAL table (the classic 2
  // classes x C2 rows); with constraint slots (a.S > 0), tables 1+s
  // are per-slot SUB-tables — the same two top-C2 classes restricted
  // to slot s's domain nodes, all rebuilt from the ONE pass-A sweep of
  // a refresh. A task with slot s serves from table 1+s, so a gang
  // whose tasks rotate domains never forces per-task refreshes (the
  // group CONTENT stays the base content; rotating groups were the
  // 19x constrained-kernel regression).
  //
  // Exactness per table: placements between refreshes are bounded by
  // the shared touch budget (< C2), every placement lands its node in
  // EVERY table whose domain contains it (updated in place when
  // present, INSERTED into the table's overflow region when not — a
  // slot task's placement is otherwise invisible to the global table
  // and vice versa), so all state-changed nodes are in-table and every
  // untouched out-of-table node stays dominated by an untouched
  // in-table entry of its own (class, slot) — the same argument as the
  // single-table case. Overflow capacity C2 can't exhaust within a
  // budget window; if a rollback-leaked slot ever would, the table set
  // is dropped and the next serve refreshes (exact, just slower).
  struct Row {
    int32_t gidx;       // -1 = dead
    float stat;         // static score column
    float pack;         // pack column (pack_eff at refresh + hits)
    float ntasks, maxt; // f32 like the XLA table
    float idle[8], fut[8], alloc[8];       // [R] (R <= 8 enforced)
    float score;        // cached serve score
    uint8_t fi, ff;     // cached fits per class
  };
  int S_eff = 0;        // active slot count (0 = no slot inputs)
  int TT = 1;           // table count = 1 + S_eff
  int OV = 0;           // shared overflow rows per table (C2 when slots)
  int STRIDE = 0;       // rows per table = 2*C2 + OV
  std::vector<Row> rows;                  // [TT*STRIDE]
  std::vector<float> s_idle, s_fut;       // [TT*STRIDE] masked serve scores
  std::vector<int32_t> rowmap_i, rowmap_f;
  std::vector<int32_t> rowmap_ep;         // [TT*N]
  std::vector<int32_t> ov_used;           // [TT] overflow rows consumed
  std::vector<uint8_t> serve_valid_t, serve_sb_t;   // [TT]
  // node -> member slot list (CSR over slot_ok, built once)
  std::vector<int32_t> mem_off, mem_slot;
  int32_t rowmap_gen = 1;
  int table_group = -1;
  int verified_group = -1;                // last group memcmp'd == table's
  int32_t table_bucket = -2;
  int touched = 0;                        // gross serves since refresh
  bool have_table = false;

  // stats (VOLCANO_NATIVE_STATS=1)
  bool stats = false;
  int64_t t_refresh = 0, t_memcmp = 0, t_serve = 0, t_apply = 0;
  int64_t n_refresh = 0, n_memcmp = 0, n_serve = 0, n_rollback = 0;
  int64_t t_passa = 0, t_passb = 0, t_install = 0;
  static inline int64_t now_ns() {
    return std::chrono::duration_cast<std::chrono::nanoseconds>(
        std::chrono::steady_clock::now().time_since_epoch()).count();
  }

  // undo log for the current gang (pre-placement values). Row indices
  // are only meaningful for the rowmap generation they were recorded
  // under: a mid-gang refresh() reinstalls the tables and reassigns the
  // slots, so each entry carries its generation and rollback discards
  // the tables instead of restoring rows across generations. Saved row
  // copies and inserted-row indices live in shared arenas (ranges per
  // entry) so multi-table placements don't heap-allocate per undo.
  struct SavedRow { int32_t k; Row row; };
  struct Undo {
    int32_t node;
    float idle[8], fut[8];
    int32_t ntasks;
    int32_t saved_lo, saved_hi;   // range in saved_arena
    int32_t ins_lo, ins_hi;       // range in ins_arena
    int32_t gen;         // rowmap_gen at record time
  };
  std::vector<Undo> undo;
  std::vector<SavedRow> saved_arena;
  std::vector<int32_t> ins_arena;

  explicit Solver(const Args& args)
      : a(args), N(args.N), R(args.R) {
    stats = std::getenv("VOLCANO_NATIVE_STATS") != nullptr;
    w.binpack_res = a.binpack_res;
    w.binpack = a.w_binpack; w.least = a.w_least;
    w.most = a.w_most; w.balanced = a.w_balanced;
    idleT.resize((size_t)R * N);
    futT.resize((size_t)R * N);
    allocT.resize((size_t)R * N);
    for (int n = 0; n < N; ++n)
      for (int r = 0; r < R; ++r) {
        idleT[(size_t)r * N + n] = a.node_idle[(size_t)n * R + r];
        futT[(size_t)r * N + n] = a.node_future[(size_t)n * R + r];
        allocT[(size_t)r * N + n] = a.node_alloc[(size_t)n * R + r];
      }
    ntasks.assign(a.node_ntasks, a.node_ntasks + N);
    pack_val.assign(N, 0.0f);
    pack_epoch.assign(N, 0);
    q_alloc.assign(a.q_alloc0, a.q_alloc0 + (size_t)a.Q * R);
    ns_alloc.assign(a.ns_alloc0, a.ns_alloc0 + (size_t)a.NS * R);
    p_cursor.assign(a.P, 0);
    ready.assign(a.J, 0);
    kept.assign(a.J, 0);
    sw_rank.assign(N, NEG);
    sw_serve.assign(N, NEG);
    sw_fi.assign(N, 0);
    sw_ff.assign(N, 0);
    S_eff = (a.S > 0 && a.task_slot && a.slot_ok) ? a.S : 0;
    TT = 1 + S_eff;
    OV = S_eff > 0 ? a.C2 : 0;
    STRIDE = 2 * a.C2 + OV;
    size_t k = (size_t)TT * STRIDE;
    rows.assign(k, Row{});
    for (auto& r : rows) r.gidx = -1;
    s_idle.assign(k, NEG);
    s_fut.assign(k, NEG);
    rowmap_i.assign((size_t)TT * N, -1);
    rowmap_f.assign((size_t)TT * N, -1);
    rowmap_ep.assign((size_t)TT * N, 0);
    ov_used.assign(TT, 0);
    serve_valid_t.assign(TT, 0);
    serve_sb_t.assign(TT, 0);
    if (S_eff > 0) {
      // node -> member-slot CSR (row S, the all-true unconstrained row,
      // is a Python-side convention the sub-tables don't need)
      mem_off.assign(N + 1, 0);
      for (int s = 0; s < S_eff; ++s) {
        const uint8_t* row = &a.slot_ok[(size_t)s * N];
        for (int n = 0; n < N; ++n) mem_off[n + 1] += row[n] ? 1 : 0;
      }
      for (int n = 0; n < N; ++n) mem_off[n + 1] += mem_off[n];
      mem_slot.assign(mem_off[N], 0);
      std::vector<int32_t> cur(mem_off.begin(), mem_off.end() - 1);
      for (int s = 0; s < S_eff; ++s) {
        const uint8_t* row = &a.slot_ok[(size_t)s * N];
        for (int n = 0; n < N; ++n)
          if (row[n]) mem_slot[cur[n]++] = s;
      }
    }
  }

  inline int table_of(int32_t t_idx) const {
    if (S_eff == 0) return 0;
    int32_t s = a.task_slot[t_idx];
    return (s >= 0 && s < S_eff) ? 1 + s : 0;
  }

  inline float pack_of(int n) const {
    return pack_epoch[n] == epoch ? pack_val[n] : 0.0f;
  }

  // two-level (namespace, queue) selection (ops/allocate.make_pool_select)
  void select(int32_t* out_pool, int32_t* out_job) {
    float share[64]; uint8_t over[64];
    std::vector<float> share_v; std::vector<uint8_t> over_v;
    float* sh = share; uint8_t* ov = over;
    if (a.Q > 64) {
      share_v.resize(a.Q); over_v.resize(a.Q);
      sh = share_v.data(); ov = over_v.data();
    }
    for (int q = 0; q < a.Q; ++q) {
      sh[q] = queue_share_one(&q_alloc[(size_t)q * R],
                              &a.q_deserved[(size_t)q * R], R);
      ov[q] = queue_overused_one(&q_alloc[(size_t)q * R],
                                 &a.q_deserved[(size_t)q * R], a.eps, R);
    }
    std::vector<uint8_t> ns_has(a.NS, 0);
    for (int p = 0; p < a.P; ++p) {
      bool ok = (p_cursor[p] < a.pool_njobs[p]) && !ov[a.pool_queue[p]];
      if (ok) ns_has[a.pool_ns[p]] = 1;
    }
    int ns_sel = 0;
    {
      float best = BIG;
      for (int ns = 0; ns < a.NS; ++ns) {
        float key = a.ns_live
            ? ns_share_one(&ns_alloc[(size_t)ns * R], a.ns_total,
                           a.ns_weight[ns], R)
            : (float)ns;
        float v = ns_has[ns] ? key : BIG;
        if (v < best) { best = v; ns_sel = ns; }
      }
    }
    int psel = 0;
    {
      float best = BIG;
      for (int p = 0; p < a.P; ++p) {
        bool ok = (p_cursor[p] < a.pool_njobs[p]) && !ov[a.pool_queue[p]]
                  && (a.pool_ns[p] == ns_sel);
        float v = ok ? sh[a.pool_queue[p]] : BIG;
        if (v < best) { best = v; psel = p; }
      }
    }
    if (ns_has[ns_sel]) {
      *out_pool = psel;
      *out_job = a.pool_job_start[psel] + p_cursor[psel];
    } else {
      *out_pool = -1;
      *out_job = -1;
    }
  }

  // serve score + fits for one table row under (req, bonus, sb)
  inline void row_score(Row& r, const float* req, float bonus, bool sb,
                        int k) {
    if (r.gidx < 0) { s_idle[k] = NEG; s_fut[k] = NEG; return; }
    float static_eff = r.stat + (sb ? r.pack : 0.0f) * bonus;
    float s = node_score_base(req, r.idle, r.alloc, w, R);
    r.score = s + static_eff;
    bool pods_ok = (r.maxt == 0.0f) || (r.ntasks < r.maxt);
    r.fi = pods_ok && fits(req, r.idle, a.eps, R);
    r.ff = a.allow_pipeline && pods_ok && fits(req, r.fut, a.eps, R);
    s_idle[k] = r.fi ? r.score : NEG;
    s_fut[k] = r.ff ? r.score : NEG;
  }

  // Full node sweep: rebuild the top-C2-per-class table for group g.
  // Pass A is branchless over plane arrays (auto-vectorized); pass B
  // feeds the per-class heaps.
  void refresh(int g, int32_t b, const float* req, float bonus) {
    int64_t _t0 = stats ? now_ns() : 0;
    const uint8_t* mask = &a.group_mask[(size_t)g * N];
    const float* stat = &a.group_static[(size_t)g * N];
    bool chain = (b >= 0) && (b == cur_bucket);
    const float* eps = a.eps;

    // ---- pass A: fits + scores for every node, branchless
    float* rank = sw_rank.data();
    float* serve = sw_serve.data();
    uint8_t* fi = sw_fi.data();
    uint8_t* ff = sw_ff.data();
    for (int n = 0; n < N; ++n) {
      uint8_t ok = mask[n] &&
          ((a.node_max[n] == 0) | (ntasks[n] < a.node_max[n]));
      fi[n] = ok; ff[n] = ok;
    }
    for (int r = 0; r < R; ++r) {
      const float* ip = &idleT[(size_t)r * N];
      const float* fp = &futT[(size_t)r * N];
      float rq = req[r], ep = eps[r];
      for (int n = 0; n < N; ++n) {
        fi[n] &= (uint8_t)(rq <= ip[n] + ep);
        ff[n] &= (uint8_t)(rq <= fp[n] + ep);
      }
    }
    // score terms, accumulated per plane in node_score_base's exact order:
    // bp = acc/wsum; s = w_bp*bp; s += w_l*least; ... (see above)
    {
      float wsum = 0.0f;
      for (int r = 0; r < R; ++r) {
        bool requested = (req[r] > 0.0f) && (w.binpack_res[r] > 0.0f);
        wsum += requested ? w.binpack_res[r] : 0.0f;
      }
      wsum = std::max(wsum, 1e-9f);
      std::vector<float>& accv = sw_acc; accv.assign(N, 0.0f);
      float* acc = accv.data();
      for (int r = 0; r < R; ++r) {
        const float* ip = &idleT[(size_t)r * N];
        const float* ap = &allocT[(size_t)r * N];
        bool requested = (req[r] > 0.0f) && (w.binpack_res[r] > 0.0f);
        float wr = requested ? w.binpack_res[r] : 0.0f;
        float rq = req[r];
        for (int n = 0; n < N; ++n) {
          float al = ap[n];
          float used = al - ip[n];
          float denom = std::max(al, 1e-9f);
          float frac = (al > 0.0f) ? (used + rq) / denom : 2.0f;
          float per = (frac <= 1.0f) ? frac * 100.0f : 0.0f;
          acc[n] += per * wr;
        }
      }
      // least/most/balanced over dims 0..1
      std::vector<float>& f0v = sw_f0; f0v.resize(3 * (size_t)N);
      float* fl0 = f0v.data();         // reuse one buffer: fl,fm,fb r=0
      float* fm0 = fl0 + N;
      float* fb0 = fm0 + N;
      std::vector<float>& f1v = sw_f1; f1v.resize(3 * (size_t)N);
      float* fl1 = f1v.data();
      float* fm1 = fl1 + N;
      float* fb1 = fm1 + N;
      for (int r = 0; r < 2; ++r) {
        const float* ip = &idleT[(size_t)r * N];
        const float* ap = &allocT[(size_t)r * N];
        float rq = req[r];
        float* fl = r == 0 ? fl0 : fl1;
        float* fm = r == 0 ? fm0 : fm1;
        float* fb = r == 0 ? fb0 : fb1;
        for (int n = 0; n < N; ++n) {
          float al = ap[n];
          float u = (al - ip[n]) + rq;
          float denom = std::max(al, 1e-9f);
          bool pos = al > 0.0f;
          fl[n] = pos ? std::max(al - u, 0.0f) / denom : 0.0f;
          fm[n] = pos ? std::min(std::max(u, 0.0f), al) / denom : 0.0f;
          fb[n] = pos ? u / denom : 0.0f;
        }
      }
      float wb = w.binpack, wl = w.least, wm = w.most, wba = w.balanced;
      for (int n = 0; n < N; ++n) {
        float bp = acc[n] / wsum;
        float least = (fl0[n] * 100.0f + fl1[n] * 100.0f) / 2.0f;
        float most = (fm0[n] * 100.0f + fm1[n] * 100.0f) / 2.0f;
        float balanced = 100.0f - std::fabs(fb0[n] - fb1[n]) * 100.0f;
        // fmaf chain matches XLA's contraction (see node_score_base)
        float s = wb * bp;
        s = std::fmaf(wl, least, s);
        s = std::fmaf(wm, most, s);
        s = std::fmaf(wba, balanced, s);
        // rank = (s + static) + pack_eff*bonus   (XLA refresh order)
        // serve = s + (static + pack_eff*bonus)  (XLA serve/scan order)
        float pe = chain && pack_epoch[n] == epoch ? pack_val[n] : 0.0f;
        rank[n] = (s + stat[n]) + pe * bonus;
        serve[n] = s + (stat[n] + pe * bonus);
      }
    }

    if (stats) { int64_t t = now_ns(); t_passa += t - _t0; _t0 = t; }
    // ---- pass B: per-(table, class) top-C2 heaps keyed (score asc,
    // idx desc); the global table plus one sub-table per member slot,
    // all fed from the one pass-A sweep
    int C2 = a.C2;
    struct HC { float s; int32_t n; };
    auto worse = [](const HC& x, const HC& y) {
      if (x.s != y.s) return x.s < y.s;
      return x.n > y.n;
    };
    auto heap_cmp = [&](const HC& x, const HC& y) { return !worse(x, y); };
    auto hpush = [&](std::vector<HC>& h, const HC& c) {
      if ((int)h.size() < C2) {
        h.push_back(c); std::push_heap(h.begin(), h.end(), heap_cmp);
      } else if (worse(h.front(), c)) {
        std::pop_heap(h.begin(), h.end(), heap_cmp);
        h.back() = c; std::push_heap(h.begin(), h.end(), heap_cmp);
      }
    };
    std::vector<std::vector<HC>> his(TT), hfs(TT);
    for (int t = 0; t < TT; ++t) {
      his[t].reserve(C2 + 1); hfs[t].reserve(C2 + 1);
    }
    for (int n = 0; n < N; ++n) {
      if (!(fi[n] | (a.allow_pipeline ? ff[n] : 0))) continue;
      float sb_score = rank[n];
      if (sb_score <= NEG * 0.5f) continue;   // lax.top_k dead-row cutoff
      HC c{sb_score, n};
      if (fi[n]) hpush(his[0], c);
      if (a.allow_pipeline && ff[n]) hpush(hfs[0], c);
      if (S_eff > 0)
        for (int mi = mem_off[n]; mi < mem_off[n + 1]; ++mi) {
          int t = 1 + mem_slot[mi];
          if (fi[n]) hpush(his[t], c);
          if (a.allow_pipeline && ff[n]) hpush(hfs[t], c);
        }
    }
    if (stats) { int64_t t = now_ns(); t_passb += t - _t0; _t0 = t; }
    // ---- install rows + serve caches (values straight from pass A)
    rowmap_gen++;
    auto install = [&](std::vector<HC>& h, int tt, int base,
                       bool is_idle_class, int width) {
      int cnt = (int)h.size();
      int32_t* rep = &rowmap_ep[(size_t)tt * N];
      int32_t* ri = &rowmap_i[(size_t)tt * N];
      int32_t* rf = &rowmap_f[(size_t)tt * N];
      for (int i = 0; i < width; ++i) {
        int k = base + i;
        Row& r = rows[k];
        if (i < cnt) {
          int n = h[i].n;
          r.gidx = n;
          r.stat = stat[n];
          r.pack = chain && pack_epoch[n] == epoch ? pack_val[n] : 0.0f;
          r.ntasks = (float)ntasks[n];
          r.maxt = (float)a.node_max[n];
          for (int rr = 0; rr < R; ++rr) {
            r.idle[rr] = idleT[(size_t)rr * N + n];
            r.fut[rr] = futT[(size_t)rr * N + n];
            r.alloc[rr] = allocT[(size_t)rr * N + n];
          }
          r.score = serve[n];
          r.fi = fi[n];
          r.ff = a.allow_pipeline ? ff[n] : 0;
          s_idle[k] = r.fi ? r.score : NEG;
          s_fut[k] = r.ff ? r.score : NEG;
          if (rep[n] != rowmap_gen) {
            rep[n] = rowmap_gen;
            ri[n] = -1; rf[n] = -1;
          }
          if (is_idle_class) ri[n] = k;
          else rf[n] = k;
        } else {
          r.gidx = -1;
          s_idle[k] = NEG;
          s_fut[k] = NEG;
        }
      }
    };
    for (int t = 0; t < TT; ++t) {
      int base = t * STRIDE;
      // layout per table: [C2 idle-built][OV shared overflow][C2 fut-
      // built]; the overflow region is dead-filled here and consumed by
      // apply-time insertions
      install(his[t], t, base, true, C2 + OV);
      install(hfs[t], t, base + C2 + OV, false, C2);
      ov_used[t] = 0;
      serve_valid_t[t] = 1;
      serve_sb_t[t] = chain ? 1 : 0;
    }
    table_group = g;
    verified_group = g;
    table_bucket = b;
    touched = 0;
    have_table = true;
    if (stats) t_install += now_ns() - _t0;
  }

  std::vector<float> sw_acc, sw_f0, sw_f1;   // refresh scratch

  inline bool same_content(int g1, int g2) const {
    if (g1 == g2) return true;
    if (g1 < 0 || g2 < 0) return false;
    if (a.pack_bonus[g1] != a.pack_bonus[g2]) return false;
    if (std::memcmp(&a.group_req[(size_t)g1 * R],
                    &a.group_req[(size_t)g2 * R], R * sizeof(float)))
      return false;
    if (std::memcmp(&a.group_mask[(size_t)g1 * N],
                    &a.group_mask[(size_t)g2 * N], N)) return false;
    if (std::memcmp(&a.group_static[(size_t)g1 * N],
                    &a.group_static[(size_t)g2 * N],
                    (size_t)N * sizeof(float))) return false;
    return true;
  }

  void run() {
    int32_t cur_pool, cur_job;
    select(&cur_pool, &cur_job);
    int32_t t_off = 0, placed = 0, placed_alloc = 0;
    std::vector<float> placed_res(R, 0.0f);
    for (int32_t step = 0; step < a.T && cur_job >= 0; ++step) {
      int job = cur_job;
      int32_t t_idx = a.job_start[job] + t_off;
      if (t_idx > a.T - 1) t_idx = a.T - 1;
      if (t_idx < 0) t_idx = 0;
      int g = a.task_group[t_idx];
      int32_t b = a.task_bucket[t_idx];
      bool valid = a.task_valid[t_idx] && (t_off < a.job_ntasks[job]);
      const float* req = &a.group_req[(size_t)g * R];
      float bonus = a.pack_bonus[g];
      bool sb = (b >= 0) && (b == cur_bucket);

      bool placed_ok = false, pipelined = false;
      int32_t sel = -1;
      if (valid) {
        // table validity: touch budget + bucket-chain + group CONTENT
        // (memcmp once per group transition, cached in verified_group)
        bool content_ok = have_table &&
            (g == table_group || g == verified_group);
        if (have_table && !content_ok) {
          int64_t t0 = stats ? now_ns() : 0;
          if (same_content(g, table_group)) {
            verified_group = g;
            content_ok = true;
          }
          if (stats) { t_memcmp += now_ns() - t0; n_memcmp++; }
        }
        bool need = !have_table || touched >= a.C2 ||
                    b != table_bucket || !content_ok;
        if (need) {
          int64_t t0 = stats ? now_ns() : 0;
          refresh(g, b, req, bonus);
          if (stats) { t_refresh += now_ns() - t0; n_refresh++; }
        }
        int tt = table_of(t_idx);
        int base = tt * STRIDE;
        if (!need && (!serve_valid_t[tt] ||
                      (serve_sb_t[tt] != 0) != sb)) {
          // serve-cache rebuild over THIS table's rows only (lazy per
          // table); exact because the serving group's content equals
          // the table group's (verified)
          for (int k = base; k < base + STRIDE; ++k)
            row_score(rows[k], req, bonus, sb, k);
          serve_valid_t[tt] = 1;
          serve_sb_t[tt] = sb ? 1 : 0;
        }
        // argmax over the serving table: idle fits first, ties by
        // lowest node index (the s_idle/s_fut caches of BOTH class
        // regions and the overflow carry each row's per-class scores)
        int64_t ts0 = stats ? now_ns() : 0;
        float best = NEG;
        for (int k = base; k < base + STRIDE; ++k)
          best = std::max(best, s_idle[k]);
        bool any_idle = best > NEG * 0.5f;
        const std::vector<float>& sc = any_idle ? s_idle : s_fut;
        if (!any_idle) {
          best = NEG;
          for (int k = base; k < base + STRIDE; ++k)
            best = std::max(best, sc[k]);
        }
        if (stats) { t_serve += now_ns() - ts0; n_serve++; }
        if (best > NEG * 0.5f) {
          int32_t min_idx = INT32_MAX;
          for (int k = base; k < base + STRIDE; ++k)
            if (sc[k] >= best && rows[k].gidx >= 0 &&
                rows[k].gidx < min_idx)
              min_idx = rows[k].gidx;
          sel = min_idx;
          placed_ok = true;
          pipelined = a.allow_pipeline && !any_idle;
        }
      }

      if (placed_ok) {
        int64_t ta0 = stats ? now_ns() : 0;
        bool take_idle = !pipelined;
        Undo u;
        u.node = sel;
        for (int r = 0; r < R; ++r) {
          u.idle[r] = idleT[(size_t)r * N + sel];
          u.fut[r] = futT[(size_t)r * N + sel];
        }
        u.ntasks = ntasks[sel];
        u.gen = rowmap_gen;
        u.saved_lo = (int32_t)saved_arena.size();
        u.ins_lo = (int32_t)ins_arena.size();
        // state apply (same arithmetic as the scan's .add(-req))
        for (int r = 0; r < R; ++r) {
          if (take_idle) idleT[(size_t)r * N + sel] += -req[r];
          futT[(size_t)r * N + sel] += -req[r];
        }
        ntasks[sel] += 1;
        // pack chain: pack_nodes = where(sb, pack_nodes, 0), then +1 at
        // sel (scan semantics — resets the whole array when the chain
        // breaks; epoch tags make the reset O(1))
        if (!sb) epoch++;
        if (pack_epoch[sel] != epoch) {
          pack_epoch[sel] = epoch; pack_val[sel] = 0.0f;
        }
        pack_val[sel] += 1.0f;
        // sel's rows in EVERY table whose domain holds it (global +
        // member sub-tables): update in place when present, insert into
        // the table's overflow when not — the membership half of each
        // table's dominance argument (see the table comment above)
        int tcount = 1;
        int tlist[1 + 16];
        tlist[0] = 0;
        if (S_eff > 0)
          for (int mi = mem_off[sel];
               mi < mem_off[sel + 1] && tcount < (int)(sizeof(tlist) /
                                                       sizeof(tlist[0]));
               ++mi)
            tlist[tcount++] = 1 + mem_slot[mi];
        if (S_eff > 0 &&
            mem_off[sel + 1] - mem_off[sel] > (int)(sizeof(tlist) /
                                                    sizeof(tlist[0])) - 1)
          have_table = false;   // absurd membership: refresh next serve
        for (int ti = 0; ti < tcount; ++ti) {
          int t = tlist[ti];
          size_t mslot = (size_t)t * N + sel;
          bool mapped = rowmap_ep[mslot] == rowmap_gen;
          int32_t ki = mapped ? rowmap_i[mslot] : -1;
          int32_t kf = mapped ? rowmap_f[mslot] : -1;
          if (ki < 0 && kf < 0) {
            if (S_eff == 0) continue;   // classic single-table behavior
            if (ov_used[t] >= OV) {     // can't keep the table complete
              have_table = false;
              continue;
            }
            int k = t * STRIDE + a.C2 + ov_used[t]++;
            Row& r = rows[k];
            r.gidx = sel;
            r.stat = a.group_static[(size_t)g * N + sel];
            r.pack = pack_of(sel);
            r.ntasks = (float)ntasks[sel];
            r.maxt = (float)a.node_max[sel];
            for (int rr = 0; rr < R; ++rr) {
              r.idle[rr] = idleT[(size_t)rr * N + sel];
              r.fut[rr] = futT[(size_t)rr * N + sel];
              r.alloc[rr] = allocT[(size_t)rr * N + sel];
            }
            row_score(r, req, bonus, sb, k);
            if (!mapped) {
              rowmap_ep[mslot] = rowmap_gen;
              rowmap_f[mslot] = -1;
            }
            rowmap_i[mslot] = k;
            ins_arena.push_back(k);
            continue;
          }
          for (int which = 0; which < 2; ++which) {
            int k = which == 0 ? ki : kf;
            if (k < 0 || (which == 1 && kf == ki)) continue;
            saved_arena.push_back(SavedRow{k, rows[k]});
            Row& r = rows[k];
            for (int rr = 0; rr < R; ++rr) {
              if (take_idle) r.idle[rr] += -req[rr];
              r.fut[rr] += -req[rr];
            }
            r.ntasks += 1.0f;
            r.pack += 1.0f;
            row_score(r, req, bonus, sb, k);
          }
        }
        u.saved_hi = (int32_t)saved_arena.size();
        u.ins_hi = (int32_t)ins_arena.size();
        undo.push_back(u);
        touched++;
        placed += 1;
        if (take_idle) placed_alloc += 1;
        for (int r = 0; r < R; ++r) placed_res[r] += req[r];
        a.assign[t_idx] = sel;
        a.out_pipelined[t_idx] = pipelined ? 1 : 0;
        if (stats) t_apply += now_ns() - ta0;
      } else if (!sb && valid) {
        // the scan resets pack_nodes every step the chain breaks, even
        // when nothing is placed (pack = where(sb, pack_nodes, 0))
        epoch++;
      }
      if (valid) cur_bucket = b;

      t_off += 1;

      // ---- job boundary (gang commit/rollback + charges + select)
      if (t_off >= a.job_ntasks[job]) {
        int32_t base = a.job_base[job];
        int32_t minav = a.job_min[job];
        bool is_ready = base + placed_alloc >= minav;
        bool is_kept = base + placed >= minav;
        bool keep = is_ready || is_kept;
        if (!keep) {
          // rollback: restore pre-placement values (exact — the XLA
          // kernel restores a checkpoint copy). pack chain state is NOT
          // restored (scan semantics: pack_nodes is never checkpointed),
          // and neither are the rows' pack columns — only their
          // state-dependent fields; the serve caches rebuild lazily.
          n_rollback++;
          for (auto it = undo.rbegin(); it != undo.rend(); ++it) {
            for (int r = 0; r < R; ++r) {
              idleT[(size_t)r * N + it->node] = it->idle[r];
              futT[(size_t)r * N + it->node] = it->fut[r];
            }
            ntasks[it->node] = it->ntasks;
            if (it->gen != rowmap_gen) {
              // recorded before a mid-gang refresh (touch budget hit, or
              // the gang's tasks alternate buckets): the row slots were
              // reassigned, so restoring the snapshots would write one
              // node's pre-placement state into another node's row.
              // Globals above are generation-independent and exact; drop
              // the tables and let the next serve refresh from them.
              have_table = false;
              continue;
            }
            for (int32_t si = it->saved_hi - 1; si >= it->saved_lo; --si) {
              const SavedRow& sr = saved_arena[si];
              float pk = rows[sr.k].pack;   // pack survives rollback
              rows[sr.k] = sr.row;
              rows[sr.k].pack = pk;
            }
            for (int32_t ii = it->ins_hi - 1; ii >= it->ins_lo; --ii) {
              // an apply-time overflow insertion: kill the row and its
              // rowmap entry (the overflow slot itself stays consumed —
              // the exhaustion valve drops the tables if that ever bites)
              int k = ins_arena[ii];
              Row& r = rows[k];
              if (r.gidx >= 0) {
                size_t mslot = (size_t)(k / STRIDE) * N + r.gidx;
                if (rowmap_ep[mslot] == rowmap_gen &&
                    rowmap_i[mslot] == k)
                  rowmap_i[mslot] = -1;
              }
              r.gidx = -1;
              s_idle[k] = NEG;
              s_fut[k] = NEG;
            }
          }
          std::fill(serve_valid_t.begin(), serve_valid_t.end(), 0);
        }
        if (keep) {
          int p = cur_pool < 0 ? 0 : cur_pool;
          int q = a.pool_queue[p];
          int ns = a.pool_ns[p];
          for (int r = 0; r < R; ++r) {
            q_alloc[(size_t)q * R + r] += placed_res[r];
            ns_alloc[(size_t)ns * R + r] += placed_res[r];
          }
        }
        if (cur_pool >= 0) p_cursor[cur_pool] += 1;
        if (is_ready) ready[job] = 1;
        if (is_kept) kept[job] = 1;
        undo.clear();
        saved_arena.clear();
        ins_arena.clear();
        t_off = 0; placed = 0; placed_alloc = 0;
        std::fill(placed_res.begin(), placed_res.end(), 0.0f);
        select(&cur_pool, &cur_job);
      }
    }

    // post-filter: placements of non-kept jobs are cleared
    for (int32_t t = 0; t < a.T; ++t) {
      int j = a.task_job[t];
      bool ok = a.task_valid[t] && j >= 0 && j < a.J &&
                (ready[j] || kept[j]);
      if (!ok) { a.assign[t] = -1; a.out_pipelined[t] = 0; }
    }
    std::memcpy(a.out_ready, ready.data(), a.J);
    std::memcpy(a.out_kept, kept.data(), a.J);
    for (int n = 0; n < N; ++n)
      for (int r = 0; r < R; ++r)
        a.out_idle[(size_t)n * R + r] = idleT[(size_t)r * N + n];
    if (stats)
      std::fprintf(stderr,
                   "[native] refresh %lldms x%lld (A %lld B %lld inst "
                   "%lld) | memcmp %lldms x%lld | "
                   "serve %lldms x%lld | apply %lldms | rollback x%lld\n",
                   (long long)(t_refresh / 1000000), (long long)n_refresh,
                   (long long)(t_passa / 1000000),
                   (long long)(t_passb / 1000000),
                   (long long)(t_install / 1000000),
                   (long long)(t_memcmp / 1000000), (long long)n_memcmp,
                   (long long)(t_serve / 1000000), (long long)n_serve,
                   (long long)(t_apply / 1000000), (long long)n_rollback);
  }
};

}  // namespace

extern "C" int vc_gang_allocate(const Args* args) {
  if (!args || args->T < 0 || args->N <= 0 || args->R <= 0 ||
      args->R > 8 || args->C2 <= 0 || args->S < 0 ||
      (args->S > 0 && (!args->task_slot || !args->slot_ok)))
    return 1;
  for (int32_t t = 0; t < args->T; ++t) {
    args->assign[t] = -1;
    args->out_pipelined[t] = 0;
  }
  std::memset(args->out_ready, 0, args->J);
  std::memset(args->out_kept, 0, args->J);
  Solver s(*args);
  s.run();
  return 0;
}

extern "C" int vc_abi_version() { return 2; }
