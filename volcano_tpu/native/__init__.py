"""Native runtime pieces: the C++ CPU solver and C snapshot accelerators.

Sources ship with the package (solver.cc, fastmodel.c) and are compiled on
demand by :mod:`volcano_tpu.native.build`; everything degrades gracefully
to the XLA/pure-Python paths when no toolchain is present.
"""
