"""Build the native solver shared library on demand.

The native runtime pieces of this framework are C++ (the reference's hot
path is native Go; ours is a C++ kernel for off-TPU deployments plus the
Pallas kernel on TPU). The library is compiled once per source change with
the toolchain baked into the image (g++); no network, no pip.

Float parity with XLA:CPU requires IEEE value semantics (no -ffast-math —
no reassociation) AND matching XLA's FMA behavior: XLA:CPU's LLVM backend
CONTRACTS the score formula's mul+add accumulation chain. The build
compiles with -ffp-contract=off and solver.cc spells that one chain as
explicit std::fmaf calls (node_score_base / row_score) — fusing exactly
the sites XLA fuses and nothing else. Blanket -ffp-contract=fast was
tried first and broke parity the other way (gcc over-fused sites XLA
leaves unfused); with no fusing at all, near-tie scores differed by 1-2
ulp and flipped argmax tie-breaks. The adversarial near-tie fuzz in
tests/test_native_kernel.py pins this; if a future XLA changes emission,
that fuzz fails and the solver conf falls back to `kernel: chunked`.
"""

from __future__ import annotations

import hashlib
import logging
import os
import subprocess
import threading

_log = logging.getLogger(__name__)
_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "solver.cc")
_LOCK = threading.Lock()
_cached_path: dict = {}      # sanitize mode -> built .so path

# -- sanitizer build mode (make sanitize; docs/design/static_analysis.md) --
#
# VOLCANO_SANITIZE=address,undefined rebuilds BOTH natives under
# ASan/UBSan. The mode is part of the artifact name, so a sanitized .so
# can never shadow a production one (and vice versa): the production
# hash scheme stays untouched and the two coexist in this directory.
# Loading a sanitized .so into an uninstrumented python requires the
# sanitizer runtimes to be LD_PRELOADed — tools/sanitize_gate.py does
# that; without the preload the dlopen fails and callers take their
# normal Python fallbacks.
_SANITIZERS = {"address": "asan", "undefined": "ubsan"}


def sanitize_mode() -> str:
    """Normalized VOLCANO_SANITIZE artifact tag ('' when off), e.g.
    ``address,undefined`` -> ``asan-ubsan``. Unknown sanitizers raise —
    a typo must not silently build an unsanitized artifact under a
    sanitized-looking gate."""
    raw = os.environ.get("VOLCANO_SANITIZE", "").strip()
    if not raw:
        return ""
    parts = sorted({p.strip() for p in raw.split(",") if p.strip()})
    unknown = [p for p in parts if p not in _SANITIZERS]
    if unknown:
        raise RuntimeError(
            f"VOLCANO_SANITIZE: unknown sanitizer(s) {unknown}; "
            f"supported: {sorted(_SANITIZERS)}")
    return "-".join(_SANITIZERS[p] for p in parts)


def _sanitize_cflags() -> list:
    raw = os.environ.get("VOLCANO_SANITIZE", "").strip()
    if not raw:
        return []
    parts = sorted({p.strip() for p in raw.split(",") if p.strip()})
    return [f"-fsanitize={','.join(parts)}", "-fno-omit-frame-pointer",
            "-g"]


def _host_tag() -> str:
    """Cache key component for the HOST the library was compiled on:
    -march=native binaries must never be reused on a different CPU (a
    foreign .so hash-matching the source would SIGILL the scheduler)."""
    import platform
    cpu = ""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("model name", "flags")):
                    cpu += line
                    if cpu.count("\n") >= 2:
                        break
    except OSError:
        pass
    h = hashlib.sha256((platform.machine() + cpu).encode()).hexdigest()[:8]
    return f"{platform.machine()}-{h}"


def _src_tag() -> str:
    with open(_SRC, "rb") as f:
        return hashlib.sha256(f.read()).hexdigest()[:16]


def lib_path() -> str:
    """Path of the built library for the current source (not yet built).
    A VOLCANO_SANITIZE mode lands in the name — distinct artifact hash
    space, so sanitized and production builds never shadow each other."""
    mode = sanitize_mode()
    suffix = f"-{mode}" if mode else ""
    return os.path.join(
        _DIR, f"libvcsolver-{_src_tag()}-{_host_tag()}{suffix}.so")


def ensure_built() -> str:
    """Compile solver.cc if needed; returns the .so path.

    Raises on compiler failure — callers gate on availability and fall
    back to the XLA kernels.
    """
    mode = sanitize_mode()
    with _LOCK:
        cached = _cached_path.get(mode)
        if cached is not None and os.path.exists(cached):
            return cached
        path = lib_path()
        if not os.path.exists(path):
            tmp = path + f".tmp{os.getpid()}"
            # -march=native vectorizes the sweep (AVX2/AVX-512 where the
            # host has it) — elementwise IEEE float ops are identical per
            # lane; -ffp-contract=off keeps gcc from fusing anything on
            # its own — XLA:CPU's FMA contraction is reproduced by the
            # explicit fmaf chain in solver.cc (see module docstring);
            # -fno-trapping-math lets the compiler
            # speculate the masked divisions (if-conversion), enabling
            # vectorization — computed VALUES stay IEEE-exact
            cmd = ["g++", "-O3", "-fPIC", "-shared", "-std=c++17",
                   "-fno-fast-math", "-ffp-contract=off", "-march=native",
                   "-fno-trapping-math", "-fno-math-errno",
                   *_sanitize_cflags(),
                   "-o", tmp, _SRC]
            _log.info("building native solver: %s", " ".join(cmd))
            r = subprocess.run(cmd, capture_output=True, text=True,
                               timeout=300)
            if r.returncode != 0:
                raise RuntimeError(
                    f"native solver build failed:\n{r.stderr[-2000:]}")
            os.replace(tmp, path)   # atomic: concurrent builders race safely
            # drop superseded hashes: every source edit used to leave its
            # build artifact behind and the directory accumulated stale
            # .so files. Unlinking is safe even for a library a running
            # process still maps (the inode lives until unmapped). The
            # sweep is scoped to this build's sanitize mode — see
            # _clean_superseded.
            _clean_superseded("libvcsolver-", path)
        _cached_path[mode] = path
        return path


# a .tmp file younger than this is treated as another builder's
# in-flight output, never cleanup fodder (the os.replace publish is
# atomic; deleting a live tmp would break that race-safety)
_TMP_STALE_SECONDS = 600.0


def _clean_superseded(prefix: str, keep: str) -> None:
    """Best-effort removal of older-hash build artifacts sharing
    ``prefix``, plus .tmp files ORPHANED by crashed builds (age-gated:
    a fresh tmp belongs to a concurrent builder about to os.replace).

    The sweep stays inside ``keep``'s hash space: a production build
    reaps only unsanitized names, a sanitized build only names carrying
    the SAME sanitize tag — the two can never shadow or delete each
    other, and neither accumulates unboundedly."""
    import time
    keep_name = os.path.basename(keep)
    keep_tags = {tag for tag in _SANITIZERS.values()
                 if f"-{tag}" in keep_name}
    try:
        for name in os.listdir(_DIR):
            if not name.startswith(prefix):
                continue
            if name == keep_name:
                continue
            tags = {tag for tag in _SANITIZERS.values()
                    if f"-{tag}" in name}
            if tags != keep_tags:
                continue
            path = os.path.join(_DIR, name)
            try:
                if ".so.tmp" in name:
                    if time.time() - os.path.getmtime(path) \
                            < _TMP_STALE_SECONDS:
                        continue   # in-flight concurrent build
                elif not name.endswith(".so"):
                    continue
                os.unlink(path)
                _log.info("removed superseded native artifact %s", name)
            except OSError:
                pass
    except OSError:
        pass


_FM_SRC = os.path.join(_DIR, "fastmodel.c")
_fm_module: dict = {}      # sanitize mode -> module
_fm_failed: dict = {}      # sanitize mode -> True


def fastmodel_path() -> str:
    """Path of the fastmodel extension for the current source + python
    + VOLCANO_SANITIZE mode (not necessarily built yet)."""
    import sys
    with open(_FM_SRC, "rb") as f:
        tag = hashlib.sha256(f.read()).hexdigest()[:16]
    tag += f"-py{sys.version_info[0]}{sys.version_info[1]}"
    mode = sanitize_mode()
    suffix = f"-{mode}" if mode else ""
    return os.path.join(_DIR, f"fastmodel-{tag}-{_host_tag()}{suffix}.so")


def fastmodel():
    """Import (building on demand) the fastmodel C extension; returns the
    module or None when the toolchain/headers are unavailable."""
    mode = sanitize_mode()
    if _fm_module.get(mode) is not None or _fm_failed.get(mode):
        return _fm_module.get(mode)
    with _LOCK:
        if _fm_module.get(mode) is not None or _fm_failed.get(mode):
            return _fm_module.get(mode)
        try:
            import importlib.util
            import sysconfig
            so = fastmodel_path()
            if not os.path.exists(so):
                inc = sysconfig.get_paths()["include"]
                tmp = so + f".tmp{os.getpid()}"
                cmd = ["gcc", "-O2", "-fPIC", "-shared", f"-I{inc}",
                       *_sanitize_cflags(),
                       "-o", tmp, _FM_SRC]
                r = subprocess.run(cmd, capture_output=True, text=True,
                                   timeout=300)
                if r.returncode != 0:
                    raise RuntimeError(
                        f"fastmodel build failed:\n{r.stderr[-1500:]}")
                os.replace(tmp, so)
                _clean_superseded("fastmodel-", so)
            spec = importlib.util.spec_from_file_location("fastmodel", so)
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
            _fm_module[mode] = mod
        except Exception as e:
            _fm_failed[mode] = True
            _log.warning("fastmodel unavailable: %s", e)
        return _fm_module.get(mode)
