"""The five BASELINE.md benchmark configs + full-cycle measurements.

Run via ``python bench.py --all`` (writes BENCH_DETAILS.json). The driver's
headline metric stays the single 50k x 10k kernel line from ``bench.py``;
this suite reports the full table:

  1 example/job.yaml-shaped single PodGroup gang (cycle sanity)
  2 1k tasks x 100 nodes, predicates + binpack (full cycle)
  3 DRF multi-queue fair-share: 4 queues, 5k tasks (full cycle)
  4 preempt victim selection: 5k starving tasks x 10k nodes (action)
  5 50k tasks x 10k nodes topology-aware (rack affinity static score):
    gang-allocate kernel, plus the node-axis-sharded variant on the mesh

plus the end-to-end ``runOnce`` (snapshot -> encode -> place -> commit)
latency at 50k x 10k — the reference's 1 s --schedule-period budget covers
runOnce (pkg/scheduler/scheduler.go:90), not just the placement math.
"""

from __future__ import annotations

import sys
import time
from typing import Dict, List, Optional

CONF_FULL = """
actions: "enqueue, allocate"
tiers:
- plugins:
  - name: priority
  - name: gang
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
  - name: binpack
"""

CONF_PREEMPT = """
actions: "preempt"
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: drf
  - name: predicates
  - name: nodeorder
"""


def log(msg: str) -> None:
    print(f"[bench-suite] {msg}", file=sys.stderr, flush=True)


def _platform() -> str:
    import jax
    return jax.devices()[0].platform


def _cycle_env(conf_text: str):
    from volcano_tpu.apiserver import ObjectStore
    from volcano_tpu.cache import SchedulerCache
    from volcano_tpu.framework import parse_scheduler_conf
    from volcano_tpu.utils.test_utils import FakeBinder, FakeEvictor

    store = ObjectStore()
    binder = FakeBinder(store)
    cache = SchedulerCache(store, binder=binder,
                           evictor=FakeEvictor(store))
    cache.run()
    return store, cache, binder, parse_scheduler_conf(conf_text)


def _run_cycle(cache, conf) -> float:
    """One measured cycle under the production GC policy: the scheduler
    loop freezes the long-lived graph and pauses cyclic GC inside runOnce
    (scheduler.py run/run_once), so the bench does the same."""
    import gc

    from volcano_tpu.framework import close_session, get_action, open_session
    from volcano_tpu.trace import tracer as tr
    from volcano_tpu.utils import gcguard

    gc.collect()
    gc.freeze()
    gcguard.pause()   # nest-safe vs the cache executor's own GC pause
    try:
        t0 = time.perf_counter()
        with tr.cycle():   # flight recorder (no-op unless tracer.enable())
            cache.begin_cycle()
            try:
                ssn = open_session(cache, conf.tiers, conf.configurations,
                                   actions=conf.actions)
                try:
                    for name in conf.actions:
                        action = get_action(name)
                        if action is not None:
                            with tr.span(f"action:{name}", action=name):
                                action.execute(ssn)
                finally:
                    close_session(ssn)
            finally:
                cache.end_cycle()
        ms = (time.perf_counter() - t0) * 1000.0
        if tr.is_enabled():
            # /debug/timeseries sample per cycle — the bench drives
            # cycles directly (no Scheduler.run_once), so it samples
            # here; the ring tail rides the bench JSON row
            from volcano_tpu.metrics import timeseries
            timeseries.sample(time.time(), extra={
                "cycle_ms": round(ms, 3), "seq": tr.current_seq()})
        return ms
    finally:
        gcguard.resume()
        gc.unfreeze()


def _populate(store, n_nodes, n_jobs, gang, queues=None, cpu="2",
              mem="4Gi", node_cpu="64", node_mem="256Gi", **constraints):
    """``constraints`` forwards populate_store's constraint-shape kwargs
    (zones / spread_every / anti_every — docs/design/constraints.md)."""
    from volcano_tpu.utils.synth import populate_store
    populate_store(store, n_nodes=n_nodes, n_jobs=n_jobs, gang_size=gang,
                   queues=queues, cpu_req=cpu, mem_req=mem,
                   node_cpu=node_cpu, node_mem=node_mem, **constraints)



def _warm_cycle(conf_text: str, runs: int = 3, flush_timeout: float = 120.0,
                **populate_kwargs):
    """Cold cycle (compile) on one env, then measured warm cycles on fresh
    identical envs with the previous env's executor drained first. Takes
    the min of ``runs`` warm measurements — single-shot wall numbers on a
    shared machine carry +-25% co-tenant noise (same protocol as
    bench.py's cycle_worker). Returns
    (ms, flush_ms, binder, cache, conf, trace_record) of the winning env
    (trace_record is the flight-recorder CycleRecord of the winning cycle,
    None unless tracing is enabled)."""
    from volcano_tpu.trace import tracer as tr

    store, cache, binder, conf = _cycle_env(conf_text)
    _populate(store, **populate_kwargs)
    _run_cycle(cache, conf)                # includes compile
    cache.flush_executors(timeout=flush_timeout)
    cache.stop()                           # free the cold env before the
    #                                        measured runs — the executor
    #                                        thread pins the env alive, so
    #                                        without stop() every env
    #                                        leaks and later runs pay the
    #                                        accumulated heap pressure
    del store, cache, binder
    best = (float("inf"), 0.0, None, None, None, None)
    for _ in range(runs):
        store2, cache2, binder2, conf2 = _cycle_env(conf_text)
        _populate(store2, **populate_kwargs)
        ms = _run_cycle(cache2, conf2)
        rec = tr.last_record() if tr.is_enabled() else None
        t0 = time.perf_counter()
        cache2.flush_executors(timeout=flush_timeout)
        flush_ms = (time.perf_counter() - t0) * 1000.0
        if ms < best[0]:
            if best[3] is not None:
                best[3].stop()             # non-winning env: release it
            best = (ms, flush_ms, binder2, cache2, conf2, rec)
        else:
            cache2.stop()
    return best


def config_1() -> Dict:
    """Single gang-of-3 PodGroup (example/job.yaml shape), full cycle."""
    ms, _, binder, _, _, _ = _warm_cycle(CONF_FULL, n_nodes=4,
                                         n_jobs=1, gang=3, node_cpu="8",
                                         node_mem="16Gi")
    assert len(binder.binds) == 3, binder.binds
    return {"config": 1, "desc": "single gang-of-3 PodGroup, full cycle",
            "value_ms": round(ms, 2), "binds": len(binder.binds),
            "platform": _platform()}


def config_2() -> Dict:
    """1k tasks x 100 nodes, predicates + binpack, full cycle."""
    ms, _, binder, _, _, _ = _warm_cycle(CONF_FULL, n_nodes=100,
                                         n_jobs=125, gang=8)
    return {"config": 2, "desc": "1k tasks x 100 nodes full cycle",
            "value_ms": round(ms, 2), "binds": len(binder.binds),
            "platform": _platform()}


def config_3() -> Dict:
    """DRF multi-queue fair share: 4 queues, 5k tasks, full cycle."""
    queues = [(f"q{i}", w) for i, w in enumerate([1, 2, 3, 4])]
    ms, _, binder, _, _, _ = _warm_cycle(CONF_FULL, n_nodes=1000,
                                         n_jobs=625, gang=8, queues=queues)
    return {"config": 3,
            "desc": "drf 4-queue fair share, 5k tasks x 1k nodes full cycle",
            "value_ms": round(ms, 2), "binds": len(binder.binds),
            "platform": _platform()}


def config_4(n_nodes=10000, n_low=1250, n_high=625) -> Dict:
    """Preempt victim selection at 5k starving tasks x 10k nodes."""
    from volcano_tpu.framework import get_action, open_session
    from volcano_tpu.models.objects import ObjectMeta, PriorityClass
    from volcano_tpu.utils.test_utils import (build_node, build_pod,
                                              build_pod_group, build_queue)

    store, cache, binder, conf = _cycle_env(CONF_PREEMPT)
    store.create("queues", build_queue("default", weight=1))
    store.create("priorityclasses",
                 PriorityClass(metadata=ObjectMeta(name="high"), value=100))
    store.create("priorityclasses",
                 PriorityClass(metadata=ObjectMeta(name="low"), value=1))
    for i in range(n_nodes):
        store.create("nodes", build_node(f"node-{i}",
                                         {"cpu": "16", "memory": "32Gi"}))
    for j in range(n_low):
        store.create("podgroups", build_pod_group(
            f"lo-{j}", "ns1", "default", 8, phase="Running",
            priority_class="low"))
        for t in range(8):
            store.create("pods", build_pod(
                "ns1", f"lo-{j}-{t}", f"node-{(j * 8 + t) % n_nodes}",
                "Running", {"cpu": "14", "memory": "28Gi"}, f"lo-{j}"))
    for j in range(n_high):
        store.create("podgroups", build_pod_group(
            f"hi-{j}", "ns1", "default", 8, phase="Inqueue",
            priority_class="high"))
        for t in range(8):
            store.create("pods", build_pod(
                "ns1", f"hi-{j}-{t}", "", "Pending",
                {"cpu": "8", "memory": "16Gi"}, f"hi-{j}"))
    cache.begin_cycle()    # production runs actions inside a cycle window
    try:
        ssn = open_session(cache, conf.tiers, conf.configurations)
        t0 = time.perf_counter()
        get_action("preempt").execute(ssn)
        ms = (time.perf_counter() - t0) * 1000.0
    finally:
        cache.end_cycle()
    from volcano_tpu.models.job_info import TaskStatus
    evicted = sum(1 for j in ssn.jobs.values() for t in j.tasks.values()
                  if t.status == TaskStatus.Releasing)
    return {"config": 4,
            "desc": f"preempt {n_high * 8} starving x {n_nodes} nodes",
            "value_ms": round(ms, 2), "evicted": evicted,
            "platform": _platform()}


def config_5(n_tasks=50_000, n_nodes=10_000, runs=3,
             sharded_devices: Optional[int] = None) -> List[Dict]:
    """50k x 10k rack-affinity kernel: single device + sharded mesh."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from volcano_tpu.ops.allocate import gang_allocate_chunked
    from volcano_tpu.ops.score import ScoreWeights
    from volcano_tpu.utils.synth import synth_arrays

    out: List[Dict] = []
    sa = synth_arrays(n_tasks, n_nodes, gang_size=8, seed=42,
                      utilization=0.3, rack_affinity=True)
    weights = ScoreWeights.make(sa.group_req.shape[1], binpack=1.0)
    args = [jnp.asarray(a) for a in sa.args] + [weights]
    r = gang_allocate_chunked(*args)
    jax.block_until_ready(r[0])
    best = float("inf")
    for _ in range(runs):
        t0 = time.perf_counter()
        r = gang_allocate_chunked(*args)
        jax.block_until_ready(r[0])
        best = min(best, (time.perf_counter() - t0) * 1000.0)
    out.append({"config": 5,
                "desc": f"{n_tasks // 1000}k x {n_nodes // 1000}k "
                        "rack-affinity gang-allocate kernel (chunked)",
                "value_ms": round(best, 2),
                "platform": _platform()})

    # the off-TPU production kernel (solver `auto` picks it): native C++ —
    # decisions verified against the XLA result on this exact production
    # shape, every bench run (a divergent solver must never publish a
    # fast number for wrong placements). Equality is up to sub-ulp score
    # ties: XLA's fused-emission float results are context-dependent, so
    # bit-identical argmax on EXACT ties is unattainable across backends
    # (the Pallas kernel carries the same contract —
    # tests/test_pallas_allocate.py); gang outcomes and placement counts
    # must match exactly and every native placement must replay feasibly.
    from volcano_tpu.ops.native import available, gang_allocate_native
    if _platform() != "tpu" and available():
        r2 = gang_allocate_native(*sa.args, weights)
        a1, a2 = np.asarray(r[0]), r2[0]
        assert np.array_equal(np.asarray(r[2]), r2[2]) \
            and np.array_equal(np.asarray(r[3]), r2[3]), \
            "native solver gang outcomes diverged at 50k x 10k"
        assert int((a1 >= 0).sum()) == int((a2 >= 0).sum()), \
            "native solver placement count diverged at 50k x 10k"
        ndiff = int((a1 != a2).sum())
        if ndiff:
            log(f"config_5: native vs XLA differ on {ndiff} sub-ulp "
                "score-tie placements (contract: tie-equivalent)")
            idle_chk = np.asarray(sa.node_idle, np.float32).copy()
            gr = np.asarray(sa.group_req, np.float32)
            tg = np.asarray(sa.task_group)
            for t in np.flatnonzero(a2 >= 0):
                idle_chk[a2[t]] -= gr[tg[t]]
            assert (idle_chk >= -np.asarray(sa.eps)[None, :] - 1e-3).all(), \
                "native placements do not replay feasibly"
        best = float("inf")
        for _ in range(runs):
            t0 = time.perf_counter()
            r2 = gang_allocate_native(*sa.args, weights)
            best = min(best, (time.perf_counter() - t0) * 1000.0)
        out.append({"config": 5,
                    "desc": f"{n_tasks // 1000}k x {n_nodes // 1000}k "
                            "rack-affinity kernel (native C++, the "
                            "off-TPU production path)",
                    "value_ms": round(best, 2),
                    "platform": _platform()})

    if sharded_devices and len(jax.devices()) >= sharded_devices:
        from jax.sharding import Mesh

        from volcano_tpu.ops.sharded import (make_sharded_gang_allocate,
                                             shard_synth)
        mesh = Mesh(np.array(jax.devices()[:sharded_devices]), ("nodes",))
        n_pad = ((n_nodes + sharded_devices - 1) // sharded_devices) \
            * sharded_devices
        sa2 = synth_arrays(n_tasks, n_nodes, gang_size=8, seed=42,
                           utilization=0.3, rack_affinity=True,
                           node_pad_to=max(n_pad, 256))
        fn = make_sharded_gang_allocate(mesh)
        sargs = shard_synth(mesh, sa2)
        r = fn(*sargs, weights)
        jax.block_until_ready(r[0])
        best = float("inf")
        for _ in range(runs):
            t0 = time.perf_counter()
            r = fn(*sargs, weights)
            jax.block_until_ready(r[0])
            best = min(best, (time.perf_counter() - t0) * 1000.0)
        out.append({"config": 5,
                    "desc": f"same, node-axis sharded over "
                            f"{sharded_devices}-device mesh",
                    "value_ms": round(best, 2),
                    "platform": _platform()})
    return out


def full_cycle_50k(n_tasks=50_000, n_nodes=10_000) -> Dict:
    """End-to-end runOnce at 50k x 10k through the store-backed cache."""
    from volcano_tpu.trace import tracer as tr

    tr.enable()   # BENCH rows carry per-phase attribution from now on
    log(f"building {n_tasks}x{n_nodes} cluster through the store "
        "(this takes a while)")
    warm, flush_ms, binder2, cache2, conf2, rec = _warm_cycle(
        CONF_FULL, flush_timeout=600.0,
        n_nodes=n_nodes, n_jobs=n_tasks // 8, gang=8)
    # the steady-state duty cycle: everything bound, nothing pending —
    # what the scheduler runs every period between arrivals (on the
    # winning env, whose flush completed)
    steady = min(_run_cycle(cache2, conf2) for _ in range(2))
    out = {"config": "full_cycle",
           "desc": f"end-to-end runOnce {n_tasks // 1000}k tasks x "
                   f"{n_nodes // 1000}k nodes (snapshot+encode+place+"
                   "commit; min of 3 warm runs; async bind flush "
                   "reported separately)",
           "value_ms": round(warm, 2),
           "steady_state_ms": round(steady, 2),
           "bind_flush_ms": round(flush_ms, 2),
           "binds": len(binder2.binds),
           "platform": _platform()}
    if rec is not None:
        out["phases"] = tr.flat_phases(rec)
        out["flush_phases"] = tr.async_phases(rec)
        out["trace_coverage"] = tr.summary(rec)["coverage"]
    return out


def churn_load(n_nodes=10_000, resident_jobs=6_250, gang=8,
               arrival_jobs=125, cycles=50) -> Dict:
    """Sustained-churn duty cycle: ``arrival_jobs`` gangs arrive and the
    oldest as many complete EVERY cycle against a full resident cluster,
    with node churn on; cycles run back-to-back (the executor's
    write-behind backlog competes with the foreground exactly as in a
    sustained burst). Reports p50/p95 runOnce latency over ``cycles``
    measured cycles — the headline duty-cycle number (a quiet-cluster
    steady state flatters the scheduler; real clusters churn)."""
    import numpy as np

    from volcano_tpu.utils.test_utils import (build_node, build_pod,
                                              build_pod_group)

    store, cache, binder, conf = _cycle_env(CONF_FULL)
    log(f"churn_load: building resident {resident_jobs * gang} tasks "
        f"x {n_nodes} nodes")
    _populate(store, n_nodes=n_nodes, n_jobs=resident_jobs, gang=gang)
    _run_cycle(cache, conf)            # compile + place the resident set
    cache.flush_executors(timeout=600.0)

    live_jobs = list(range(resident_jobs))
    next_job = resident_jobs
    next_node = n_nodes
    lat = []
    t_wall = time.perf_counter()
    for c in range(cycles):
        # arrivals: new Inqueue gangs
        for j in range(next_job, next_job + arrival_jobs):
            store.create("podgroups", build_pod_group(
                f"pg-{j}", "default", "default", gang, phase="Inqueue"))
            for t in range(gang):
                store.create("pods", build_pod(
                    "default", f"job{j}-task{t}", "", "Pending",
                    {"cpu": "2", "memory": "4Gi"}, groupname=f"pg-{j}"))
            live_jobs.append(j)
        next_job += arrival_jobs
        # completions: the oldest gangs finish and their objects go away
        for j in live_jobs[:arrival_jobs]:
            for t in range(gang):
                try:
                    store.delete("pods", f"job{j}-task{t}", "default",
                                 skip_admission=True)
                except KeyError:
                    pass
            try:
                store.delete("podgroups", f"pg-{j}", "default",
                             skip_admission=True)
            except KeyError:
                pass
        live_jobs = live_jobs[arrival_jobs:]
        # node churn: one node leaves, a fresh one joins
        try:
            store.delete("nodes", f"node-{(next_node - n_nodes) % n_nodes}",
                         skip_admission=True)
        except KeyError:
            pass
        store.create("nodes", build_node(
            f"node-{next_node}", {"cpu": "64", "memory": "256Gi",
                                  "pods": "110"}))
        next_node += 1
        ms = _run_cycle(cache, conf)
        lat.append(ms)
    wall_s = time.perf_counter() - t_wall
    t0 = time.perf_counter()
    cache.flush_executors(timeout=600.0)
    drain_ms = (time.perf_counter() - t0) * 1000.0
    p50, p95 = np.percentile(lat, [50, 95])
    return {"config": "churn_load",
            "desc": f"sustained churn: {arrival_jobs * gang} arrivals + "
                    f"completions/cycle at {resident_jobs * gang} resident "
                    f"x {n_nodes} nodes, node churn on, {cycles} "
                    "back-to-back cycles",
            "p50_ms": round(float(p50), 2), "p95_ms": round(float(p95), 2),
            "max_ms": round(float(max(lat)), 2),
            "wall_s": round(wall_s, 1),
            "final_drain_ms": round(drain_ms, 2),
            "binds": len(binder.binds), "platform": _platform()}


CONF_RECLAIM = """
actions: "reclaim"
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
"""


def config_reclaim(n_nodes=10_000, n_running=1_250, n_pending=625) -> Dict:
    """Cross-queue reclaim at scale (reclaim.go:84-188): q-over holds the
    whole cluster with Running gangs while q-under's pending jobs reclaim
    their deserved share; measures the reclaim action's execute latency."""
    from volcano_tpu.framework import get_action, open_session
    from volcano_tpu.utils.test_utils import (build_node, build_pod,
                                              build_pod_group, build_queue)

    store, cache, binder, conf = _cycle_env(CONF_RECLAIM)
    store.create("queues", build_queue("q-over", weight=1))
    store.create("queues", build_queue("q-under", weight=1))
    for i in range(n_nodes):
        store.create("nodes", build_node(f"node-{i}",
                                         {"cpu": "16", "memory": "32Gi"}))
    for j in range(n_running):
        store.create("podgroups", build_pod_group(
            f"ov-{j}", "ns1", "q-over", 8, phase="Running"))
        for t in range(8):
            store.create("pods", build_pod(
                "ns1", f"ov-{j}-{t}", f"node-{(j * 8 + t) % n_nodes}",
                "Running", {"cpu": "14", "memory": "28Gi"}, f"ov-{j}"))
    for j in range(n_pending):
        store.create("podgroups", build_pod_group(
            f"un-{j}", "ns1", "q-under", 8, phase="Inqueue"))
        for t in range(8):
            store.create("pods", build_pod(
                "ns1", f"un-{j}-{t}", "", "Pending",
                {"cpu": "8", "memory": "16Gi"}, f"un-{j}"))
    cache.begin_cycle()
    try:
        ssn = open_session(cache, conf.tiers, conf.configurations)
        t0 = time.perf_counter()
        get_action("reclaim").execute(ssn)
        ms = (time.perf_counter() - t0) * 1000.0
    finally:
        cache.end_cycle()
    from volcano_tpu.models.job_info import TaskStatus
    evicted = sum(1 for j in ssn.jobs.values() for t in j.tasks.values()
                  if t.status == TaskStatus.Releasing)
    return {"config": "reclaim",
            "desc": f"cross-queue reclaim {n_pending * 8} reclaimers x "
                    f"{n_nodes} nodes ({n_running * 8} running victims "
                    "pool)",
            "value_ms": round(ms, 2), "evicted": evicted,
            "platform": _platform()}


def capture_traces() -> None:
    """jax.profiler trace artifacts (SURVEY §5.1), captured AFTER the
    measurements — host-side tracing inflates full-cycle latency up to
    5x, so the recorded numbers must never run under the profiler. One
    reduced-shape pass per config class: a full cycle (host+device
    overlap) and the placement kernel. Paths print to stderr; opt out
    with VOLCANO_BENCH_TRACE=0; failures never break the bench."""
    import os

    import jax
    if os.environ.get("VOLCANO_BENCH_TRACE", "1") == "0":
        return
    base = os.path.join(os.getcwd(), "traces")
    for name, fn in (("full_cycle", config_2),
                     ("kernel", lambda: config_5(5_000, 1_000))):
        path = os.path.join(base, name)
        try:
            os.makedirs(path, exist_ok=True)
            with jax.profiler.trace(path):
                fn()
            log(f"trace for {name}: {path}")
        except Exception as e:   # tracing must never fail the bench
            log(f"trace capture for {name} failed ({e})")


def machine_calibration() -> Dict:
    """Co-tenant load fingerprint: wall time of a fixed single-core numpy
    workload, recorded alongside the suite so readers can compare two
    captures' machine conditions. This box is SHARED: same-day A/B ran
    identical round-4 code at 655 ms (round-4 capture) vs 1528 ms
    (round-5 re-run) on the preempt config — up to ~2.3x wall drift.
    Round-5 observed range for this fingerprint: ~32-40 ms."""
    import numpy as np
    rng = np.random.default_rng(0)
    a = rng.random(2_000_000)
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        np.sort(a.copy())
        best = min(best, (time.perf_counter() - t0) * 1000.0)
    return {"config": "machine_calibration",
            "desc": "fixed numpy sort (2M f64), min of 3 — compare across "
                    "captures; round-5 observed ~32-40 ms",
            "value_ms": round(best, 2)}


def run_all(full_scale: bool = True) -> List[Dict]:
    import jax

    results: List[Dict] = []

    def run(name, fn):
        """Per-config isolation: one failing config must not abort the
        suite (the artifact write happens only after run_all returns)."""
        log(f"running {name}")
        try:
            r = fn()
        except Exception as e:
            log(f"{name} FAILED: {e!r}")
            results.append({"config": name, "error": repr(e)[:300]})
            return
        results.extend(r if isinstance(r, list) else [r])
        log(f"{name}: {results[-1]}")

    results.append(machine_calibration())
    log(f"calibration: {results[-1]}")
    run("config_1", config_1)
    run("config_2", config_2)
    run("config_3", config_3)
    run("config_4", config_4 if full_scale else
        lambda: config_4(n_nodes=2000, n_low=250, n_high=125))
    run("config_reclaim", config_reclaim if full_scale else
        lambda: config_reclaim(n_nodes=2000, n_running=250, n_pending=125))
    n_dev = len(jax.devices())
    run("config_5", (lambda: config_5(
        sharded_devices=n_dev if n_dev >= 2 else None)) if full_scale else
        (lambda: config_5(5_000, 1_000,
                          sharded_devices=n_dev if n_dev >= 2 else None)))
    if full_scale:
        run("full_cycle_50k", full_cycle_50k)
        run("churn_load", churn_load)
    else:
        run("churn_load", lambda: churn_load(
            n_nodes=1000, resident_jobs=625, arrival_jobs=25, cycles=10))
    results.append(machine_calibration())   # load may drift over the run
    log(f"calibration (end): {results[-1]}")
    capture_traces()
    return results
